"""Rule-based fleet health engine: structured anomaly verdicts.

The sensing half of the ROADMAP's closed-loop adaptive controller: the
aggregated fleet view (``observability/aggregate.py``) goes in, a
machine-consumable :class:`HealthReport` of :class:`Verdict` records
comes out — so the controller (and ``bfmonitor``, and CI gates) consume
VERDICTS, not raw series.  Every rule has a documented threshold with an
env knob (``BLUEFOG_HEALTH_*``), and the defaults are calibrated to
raise ZERO false alarms on a clean 20-step consensus-only reference run
(asserted by ``tests/test_fleet_health.py`` and ``make health-smoke``).

Rules over the trailing window of ``cfg.window`` steps:

* ``consensus_stall``    — consensus distance stopped contracting while
  still far from consensus: the spectral-gap contraction the paper's
  claim rests on has stalled (slow-mixing topology, dead edges, or a
  CHOCO γ backed too far off).
* ``consensus_diverge``  — consensus distance GREW by ``diverge_ratio``
  over the window: the mixing recursion is unstable.
* ``non_finite``         — NaN/inf in consensus/norm/loss series: the
  iterates are corrupt (critical).
* ``residual_blowup``    — carried error-feedback residual exceeds
  ``residual_factor`` x param norm: the documented γ≫ω instability
  boundary (docs/compression.md "γ stability").
* ``straggler``          — one rank's median step wall time exceeds
  ``straggler_factor`` x the fleet median.
* ``dead_rank``          — a rank stopped reporting ``dead_after`` steps
  ago while the fleet advanced; ``rank_silent`` — an expected rank never
  wrote a file at all.
* ``dead_rank_confirmed`` / ``repair`` / ``degraded`` — fed from the
  resilience counters (``record_resilience_event`` /
  ``bf_resilience_*``) riding the JSONL records.
* ``compile_storm``      — ``bf_step_cache_total{result=build}`` grew by
  more than ``compile_builds`` inside the window: a knob is churning the
  step cache (``utils/compile_cache.note_step_cache``).
* ``overlap_collapse``   — the measured ``overlap_efficiency`` series
  (``observability/commprof.py``: hidden / total exchange time) dropped
  below ``overlap_min``: the delayed-mix pipeline degenerated to
  synchronous — the exchange is back on the critical path.  Silent on
  runs that never probe (the clean reference emits no such field).
* ``series_gap``         — loader-level holes (truncated tails, parse
  errors, missing steps) surfaced as verdicts while the window still
  covers them (old, moved-past gaps stay in ``view.gaps`` only).
* ``no_data``            — the view is empty with nothing even expected:
  a typo'd prefix must not pass a ``--fail-on`` gate green.

Severity: ``info`` verdicts are context (repairs, chaos boundaries);
``warn``/``critical`` are ALERTS — ``report.ok`` is False iff any alert
fired.  Results are mirrored to the host registry as ``bf_health_*``
gauges and appendable to a verdict JSONL (:func:`write_verdicts`).
"""

import dataclasses
import json
import math
import os
import time
from typing import List, Optional

from . import aggregate as AG
from . import metrics as _metrics

__all__ = [
    "HealthConfig", "Verdict", "HealthReport", "evaluate",
    "write_verdicts", "UNMEASURED",
]

# mirrors ingraph.UNMEASURED without importing the JAX stack: consensus
# distance -1 means "this step issued no collective" (degraded branch)
UNMEASURED = -1.0

_ENV_PREFIX = "BLUEFOG_HEALTH_"


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(_ENV_PREFIX + name)
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(_ENV_PREFIX + name)
    return int(v) if v else default


@dataclasses.dataclass
class HealthConfig:
    """Rule thresholds (env defaults in parentheses; see
    docs/observability.md "Fleet health & bfmonitor").

    ``window``            steps per verdict window (8)
    ``stall_ratio``       stall fires when cd_end/cd_start exceeds this
                          over a FULL window (0.9 — i.e. <10% contraction)
    ``stall_floor``       ...and cd_end is still above this absolute
                          floor (1e-8): converged-and-flat is healthy
    ``diverge_ratio``     diverge fires at cd_end/cd_start above this (4)
    ``residual_factor``   residual blow-up at residual_norm > factor x
                          param_norm (1.0 — the metrics-smoke bound)
    ``straggler_factor``  rank median step time > factor x fleet median (2)
    ``straggler_floor_s`` ignore sub-floor absolute step times (1e-4:
                          microsecond jitter is not a straggler)
    ``dead_after``        rank considered dead after lagging this many
                          steps behind the fleet max (window)
    ``compile_builds``    step-cache builds tolerated per window (2)
    ``overlap_min``       overlap_collapse fires when the measured
                          overlap efficiency drops below this (0.2)
    ``overlap_samples``   ...for this many CONSECUTIVE latest samples
                          (2: one cold probe / noisy reading is not a
                          trend)
    """
    window: int = 8
    stall_ratio: float = 0.9
    stall_floor: float = 1e-8
    diverge_ratio: float = 4.0
    residual_factor: float = 1.0
    straggler_factor: float = 2.0
    straggler_floor_s: float = 1e-4
    dead_after: Optional[int] = None
    compile_builds: int = 2
    overlap_min: float = 0.2
    overlap_samples: int = 2

    @classmethod
    def from_env(cls) -> "HealthConfig":
        return cls(
            window=_env_int("WINDOW", 8),
            stall_ratio=_env_float("STALL_RATIO", 0.9),
            stall_floor=_env_float("STALL_FLOOR", 1e-8),
            diverge_ratio=_env_float("DIVERGE_RATIO", 4.0),
            residual_factor=_env_float("RESIDUAL_FACTOR", 1.0),
            straggler_factor=_env_float("STRAGGLER_FACTOR", 2.0),
            straggler_floor_s=_env_float("STRAGGLER_FLOOR_S", 1e-4),
            dead_after=(_env_int("DEAD_AFTER", 0) or None),
            compile_builds=_env_int("COMPILE_BUILDS", 2),
            overlap_min=_env_float("OVERLAP_MIN", 0.2),
            overlap_samples=_env_int("OVERLAP_SAMPLES", 2),
        )

    def resolved_dead_after(self) -> int:
        return self.dead_after if self.dead_after else self.window


@dataclasses.dataclass
class Verdict:
    """One structured health finding.

    ``rank`` is None for fleet-wide verdicts; ``value``/``threshold``
    carry the measured quantity and the rule boundary it crossed so the
    controller can reason about margins, not just booleans."""
    rule: str
    severity: str                      # info | warn | critical
    message: str
    rank: Optional[int] = None
    step_lo: Optional[int] = None
    step_hi: Optional[int] = None
    value: Optional[float] = None
    threshold: Optional[float] = None

    def asdict(self):
        d = dataclasses.asdict(self)
        # JSONL must stay strictly parseable even for inf/nan evidence
        for k in ("value", "threshold"):
            if d[k] is not None and not math.isfinite(d[k]):
                d[k] = repr(d[k])
        return d


@dataclasses.dataclass
class HealthReport:
    """Verdicts for one trailing step window (the controller contract:
    one report per evaluation, ``ok`` false iff any warn/critical)."""
    step_lo: int
    step_hi: int
    ranks: int
    verdicts: List[Verdict]

    @property
    def alerts(self) -> List[Verdict]:
        return [v for v in self.verdicts
                if v.severity in ("warn", "critical")]

    @property
    def ok(self) -> bool:
        return not self.alerts

    def by_rule(self, rule: str) -> List[Verdict]:
        return [v for v in self.verdicts if v.rule == rule]

    def asdict(self):
        return {
            "step_lo": self.step_lo, "step_hi": self.step_hi,
            "ranks": self.ranks, "ok": self.ok,
            "alerts": len(self.alerts),
            "verdicts": [v.asdict() for v in self.verdicts],
        }


def _finite(v: Optional[float]) -> bool:
    return v is not None and math.isfinite(v)


def _windowed(series, lo: int):
    return [(s, v) for s, v in series if s >= lo]


def _consensus_series(view: AG.FleetView, rank: int, lo: int):
    """Rank's consensus series inside the window, UNMEASURED (degraded
    no-collective steps) excluded — those steps measured nothing."""
    return [(s, v) for s, v in _windowed(view.series_of(
        rank, "consensus_dist"), lo) if v != UNMEASURED]


def _consensus_rules(view, cfg, lo, hi, out):
    full = cfg.window
    stalled, diverged = [], []
    evidence = {}
    for rank in view.ranks:
        cd = _consensus_series(view, rank, lo)
        if len(cd) < 2:
            continue
        vals = [v for _, v in cd]
        if not all(_finite(v) for v in vals):
            continue                       # non_finite rule owns these
        first, last = vals[0], vals[-1]
        if first <= 0:
            continue                       # already exactly at consensus
        ratio = last / first
        if ratio >= cfg.diverge_ratio:
            diverged.append(rank)
            evidence[rank] = ratio
        # stall needs a FULL window of evidence: short tails at startup
        # must not alarm
        elif (len(cd) >= full and ratio > cfg.stall_ratio
                and last > cfg.stall_floor):
            stalled.append(rank)
            evidence[rank] = ratio

    def emit(ranks, rule, severity, threshold, fmt):
        if not ranks:
            return
        if len(ranks) == len(view.ranks):
            worst = max(ranks, key=lambda r: evidence[r])
            out.append(Verdict(rule, severity,
                               fmt("all ranks", evidence[worst]),
                               rank=None, step_lo=lo, step_hi=hi,
                               value=evidence[worst], threshold=threshold))
        else:
            for r in ranks:
                out.append(Verdict(rule, severity, fmt(f"rank {r}",
                                                       evidence[r]),
                                   rank=r, step_lo=lo, step_hi=hi,
                                   value=evidence[r], threshold=threshold))

    emit(diverged, "consensus_diverge", "critical", cfg.diverge_ratio,
         lambda who, v: f"consensus distance grew {v:.3g}x over steps "
                        f"{lo}..{hi} on {who} (mixing unstable; check "
                        f"topology repair and CHOCO gamma)")
    emit(stalled, "consensus_stall", "warn", cfg.stall_ratio,
         lambda who, v: f"consensus distance contracted only "
                        f"{(1 - v) * 100:.1f}% over steps {lo}..{hi} on "
                        f"{who} while still above floor (slow-mixing "
                        f"topology or stalled exchange)")


_FINITE_FIELDS = ("consensus_dist", "param_norm", "grad_norm",
                  "update_norm", "residual_norm", "loss")


def _non_finite_rule(view, cfg, lo, hi, out):
    for rank in view.ranks:
        for field in _FINITE_FIELDS:
            bad = [(s, v) for s, v in _windowed(
                view.series_of(rank, field), lo)
                if v is not None and not math.isfinite(v)]
            if bad:
                s, v = bad[0]
                out.append(Verdict(
                    "non_finite", "critical",
                    f"rank {rank}: {field} went non-finite ({v!r}) at "
                    f"step {s} — iterates corrupt",
                    rank=rank, step_lo=s, step_hi=bad[-1][0], value=v))
                break      # one verdict per rank says it all


def _residual_rule(view, cfg, lo, hi, out):
    for rank in view.ranks:
        res = dict(_windowed(view.series_of(rank, "residual_norm"), lo))
        pn = dict(_windowed(view.series_of(rank, "param_norm"), lo))
        worst, at = 0.0, None
        for s, r in res.items():
            p = pn.get(s)
            if _finite(r) and _finite(p) and p > 0 and r / p > worst:
                worst, at = r / p, s
        if at is not None and worst > cfg.residual_factor:
            out.append(Verdict(
                "residual_blowup", "critical",
                f"rank {rank}: error-feedback residual reached "
                f"{worst:.3g}x the param norm at step {at} — the "
                f"gamma >> omega instability boundary "
                f"(docs/compression.md); back off CHOCO gamma or the "
                f"compression ratio",
                rank=rank, step_lo=lo, step_hi=hi, value=worst,
                threshold=cfg.residual_factor))


def _straggler_rule(view, cfg, lo, hi, out):
    medians = {}
    for rank in view.ranks:
        wall = [v for s, v in view.step_wall_s(rank) if s >= lo]
        if wall:
            medians[rank] = float(sorted(wall)[len(wall) // 2])
    if len(medians) < 3:
        return                       # no meaningful fleet baseline
    fleet = sorted(medians.values())[len(medians) // 2]
    if fleet < cfg.straggler_floor_s:
        return
    for rank, med in sorted(medians.items()):
        if med > cfg.straggler_factor * fleet:
            out.append(Verdict(
                "straggler", "warn",
                f"rank {rank}: median step {med * 1e3:.1f} ms is "
                f"{med / fleet:.1f}x the fleet median "
                f"{fleet * 1e3:.1f} ms over steps {lo}..{hi}",
                rank=rank, step_lo=lo, step_hi=hi, value=med / fleet,
                threshold=cfg.straggler_factor))


def _overlap_rule(view, cfg, lo, hi, out):
    """``overlap_collapse``: the measured overlap efficiency (the comm
    profiler's hidden/total exchange split) fell below ``overlap_min`` —
    the delayed-mix pipeline degenerated to synchronous.  Fires only
    when the LAST ``overlap_samples`` readings are ALL below the floor:
    the measurement subtracts two near-equal wall times, so one noisy
    sample (or one cold probe) is not a trend.  Rules only on what was
    MEASURED — a run that never probes (the clean reference) emits no
    field and stays silent."""
    for rank in view.ranks:
        eff = [(s, v) for s, v in _windowed(
            view.series_of(rank, "overlap_efficiency"), lo)
            if _finite(v)]
        if len(eff) < cfg.overlap_samples:
            continue
        step_at, latest = eff[-1]
        if all(v < cfg.overlap_min
               for _, v in eff[-cfg.overlap_samples:]):
            peak = max(v for _, v in eff)
            out.append(Verdict(
                "overlap_collapse", "warn",
                f"rank {rank}: measured overlap efficiency fell to "
                f"{latest:.2f} at step {step_at} (window peak "
                f"{peak:.2f}, floor {cfg.overlap_min:g}) — the "
                f"delayed-mix pipeline degenerated to synchronous; the "
                f"exchange is back on the critical path "
                f"(docs/observability.md \"Comm profiling\")",
                rank=rank, step_lo=lo, step_hi=hi, value=latest,
                threshold=cfg.overlap_min))


def _dead_rank_rule(view, cfg, lo, hi, out):
    dead_after = cfg.resolved_dead_after()
    for rank in view.ranks:
        last = view.rank_last_step(rank)
        if last is None:
            continue               # missing_file gap owns the no-data case
        if hi - last >= dead_after:
            out.append(Verdict(
                "dead_rank", "critical",
                f"rank {rank}: last report at step {last}, fleet is at "
                f"{hi} ({hi - last} steps behind) — rank presumed dead "
                f"or wedged",
                rank=rank, step_lo=last, step_hi=hi,
                value=float(hi - last), threshold=float(dead_after)))


_GAP_SEVERITY = {"missing_file": "critical", "truncated": "info",
                 "missing_steps": "warn", "parse_error": "warn"}


def _gap_rule(view, cfg, lo, hi, out):
    for gap in view.gaps:
        if gap.kind == "missing_file":
            out.append(Verdict(
                "rank_silent", "critical",
                f"rank {gap.rank}: expected but never wrote a series "
                f"file ({gap.detail or 'no JSONL found'})",
                rank=gap.rank, step_lo=lo, step_hi=hi))
        else:
            # a gap the fleet moved past `window` steps ago is history,
            # not an ACTIVE condition: alarming on it forever would pin
            # report.ok false for the rest of the run (it stays visible
            # in view.gaps / the bfmonitor gaps list).  Gaps with no
            # step anchor cannot be aged out and always report.
            if gap.step is not None and gap.step < lo:
                continue
            out.append(Verdict(
                "series_gap", _GAP_SEVERITY.get(gap.kind, "warn"),
                f"{gap.kind}: {gap.detail}" + (
                    f" (rank {gap.rank})" if gap.rank is not None else ""),
                rank=gap.rank, step_lo=lo, step_hi=hi))


def _counter_rules(view, cfg, lo, hi, out):
    # agg="max" throughout: every process increments its own copy of
    # these counters for the same fleet-wide event, so a fleet-summed
    # delta would scale the alarm threshold with fleet size (one
    # synchronized recompile on 8 ranks is 1 event, not 8)
    confirms = view.counter_delta("bf_resilience_confirms_total",
                                  agg="max")
    if confirms > 0:
        out.append(Verdict(
            "dead_rank_confirmed", "warn",
            f"{int(confirms)} rank death(s) majority-confirmed and the "
            f"mixing matrix repaired during the series "
            f"(bf_resilience_confirms_total)",
            step_lo=lo, step_hi=hi, value=confirms))
    for key in view.counter_keys("bf_resilience_events_total"):
        delta = view.counter_delta(key, agg="max")
        if delta <= 0:
            continue
        kind = key[key.find("kind=") + 5:].rstrip("}")
        sev = "warn" if kind in ("degraded", "fault") else "info"
        out.append(Verdict(
            "resilience_event", sev,
            f"{int(delta)} resilience event(s) of kind {kind!r} "
            f"recorded during the series",
            step_lo=lo, step_hi=hi, value=delta))
    builds = view.counter_delta("bf_step_cache_total{result=build}",
                                window=cfg.window, agg="max")
    if builds > cfg.compile_builds:
        out.append(Verdict(
            "compile_storm", "warn",
            f"{int(builds)} whole-step recompiles inside the last "
            f"{cfg.window} steps (> {cfg.compile_builds}) — a knob is "
            f"churning the step-cache key (utils/compile_cache)",
            step_lo=lo, step_hi=hi, value=builds,
            threshold=float(cfg.compile_builds)))


_SEVERITY_RANK = {"critical": 0, "warn": 1, "info": 2}

# rules with a nonzero bf_health_alerts cell from the previous
# evaluation — zeroed when they resolve
_alerted_rules = set()


def evaluate(view: AG.FleetView,
             cfg: Optional[HealthConfig] = None) -> HealthReport:
    """Run every rule over the trailing ``cfg.window`` steps of the
    fleet view; mirror the outcome to ``bf_health_*`` registry gauges
    when the host registry is enabled."""
    cfg = cfg or HealthConfig.from_env()
    steps = view.steps()
    hi = steps[-1] if steps else 0
    lo = max(steps[0] if steps else 0, hi - cfg.window + 1)
    out: List[Verdict] = []
    if steps:
        _consensus_rules(view, cfg, lo, hi, out)
        _non_finite_rule(view, cfg, lo, hi, out)
        _residual_rule(view, cfg, lo, hi, out)
        _straggler_rule(view, cfg, lo, hi, out)
        _overlap_rule(view, cfg, lo, hi, out)
        _dead_rank_rule(view, cfg, lo, hi, out)
        _counter_rules(view, cfg, lo, hi, out)
    elif not any(g.kind == "missing_file" for g in view.gaps):
        # an empty view with nothing even expected must NOT read as
        # healthy: a typo'd prefix in a `--fail-on` CI gate would
        # otherwise pass green while monitoring nothing
        out.append(Verdict(
            "no_data", "critical",
            "no series data found — wrong prefix, or the fleet never "
            "wrote a step"))
    _gap_rule(view, cfg, lo, hi, out)
    out.sort(key=lambda v: (_SEVERITY_RANK.get(v.severity, 3), v.rule,
                            -1 if v.rank is None else v.rank))
    report = HealthReport(step_lo=lo, step_hi=hi,
                          ranks=len(view.ranks), verdicts=out)
    if _metrics.enabled():
        _metrics.gauge(
            "bf_health_ok",
            "1 when the last health evaluation raised no warn/critical "
            "verdict").set(1.0 if report.ok else 0.0)
        _metrics.gauge(
            "bf_health_last_step",
            "newest step the last health evaluation saw").set(float(hi))
        alerts = _metrics.gauge(
            "bf_health_alerts",
            "active warn/critical verdicts by rule (last evaluation)")
        by_rule = {}
        for v in report.alerts:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        # an alert that resolved must drop to 0 on the scrape surface,
        # not linger at its old count
        for rule in _alerted_rules - set(by_rule):
            alerts.set(0.0, rule=rule)
        _alerted_rules.clear()
        _alerted_rules.update(by_rule)
        for rule, n in by_rule.items():
            alerts.set(float(n), rule=rule)
    return report


def write_verdicts(report: HealthReport, path: str,
                   append: bool = True) -> None:
    """Append the report to a verdict JSONL: one summary line (``kind:
    report``) then one line per verdict (``kind: verdict``) — the
    machine-consumable trail the controller tails.

    Bounded like the telemetry JSONL: when ``BLUEFOG_METRICS_MAX_MB`` is
    set and the file would exceed it, the trail rotates to
    ``<path>.1..K`` first (``export.rotate_file``) — a wedged fleet
    alarming every frame for a week must not fill the disk."""
    from . import export as _export
    max_bytes, keep = _export.resolve_rotation()
    if append and max_bytes:
        try:
            if os.path.getsize(path) >= max_bytes:
                _export.rotate_file(path, keep)
        except OSError:
            pass
    now_us = int(time.time() * 1e6)
    with open(path, "a" if append else "w") as f:
        head = {"kind": "report", "t_us": now_us}
        head.update(report.asdict())
        del head["verdicts"]
        f.write(json.dumps(head) + "\n")
        for v in report.verdicts:
            rec = {"kind": "verdict", "t_us": now_us}
            rec.update(v.asdict())
            f.write(json.dumps(rec) + "\n")
