"""Chrome-tracing timeline (reference parity: ``bluefog/common/timeline.{h,cc}``
and the Python surface ``basics.py:456-546``).

Activation mirrors the reference: set ``BLUEFOG_TIMELINE=<prefix>`` before
``bf.init()`` (or call :func:`timeline_start` explicitly) and each process
writes ``<prefix><rank>.json`` viewable in ``chrome://tracing`` / Perfetto.

Two recording paths:

* **Host activities** — op dispatch/synchronize phases recorded by the op
  layer (ENQUEUE_*, COMMUNICATE, NEGOTIATION never exists here — SPMD has no
  coordinator), plus user activities via :func:`timeline_start_activity` /
  :func:`timeline_context` exactly like the reference.  Records flow through
  the native C++ writer (``csrc/timeline.cc``: bounded MPMC ring + dedicated
  writer thread, the same design as the reference's boost SPSC queue at
  ``timeline.h:46-76``) or a pure-Python fallback when no toolchain exists.
* **Device activities** — every jitted op also runs under
  ``jax.profiler.TraceAnnotation``-compatible named scopes, so an XLA profile
  captured around the run carries matching op names.
"""

import atexit
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from . import native

__all__ = [
    "timeline_start", "timeline_end", "timeline_enabled",
    "timeline_start_activity", "timeline_end_activity", "timeline_context",
    "record_op_phase", "op_phase", "record_resilience_event",
    "record_counter", "op_start_us", "record_op_span",
    "record_gossip_round", "GOSSIP_LANE",
]

_ENV = "BLUEFOG_TIMELINE"

# largest double JSON can carry; counter samples are clamped into
# [-_JSON_MAX, _JSON_MAX] — json has no Infinity, and a diverged run
# (the one time you NEED the lane) must not corrupt the whole trace
_JSON_MAX = 1.7976931348623157e308


def _finite_counter_value(value):
    """JSON-legal float for a counter sample, or None to drop it.
    ``inf`` clamps to the double max (the lane spikes visibly instead of
    invalidating the file); ``NaN`` has no honest rendering and drops."""
    v = float(value)
    if v != v:                   # NaN
        return None
    if v == float("inf"):
        return _JSON_MAX
    if v == float("-inf"):
        return -_JSON_MAX
    return v


class _PyWriter:
    """Pure-Python fallback writer: same file format as the native one.

    Output is STRICT JSON (parses with ``json.load``): events are
    comma-separated with no trailing comma and the array is closed by
    ``close()``, which is idempotent — ``atexit``-registered
    ``timeline_end`` may run after an explicit ``timeline_end()`` already
    closed the file, and a second close must be a no-op, not a write on a
    closed handle."""

    def __init__(self, path: str, rank: int):
        self._f = open(path, "w")
        self._rank = rank
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._lanes = {}
        self._first = True
        self._closed = False
        self._f.write("[\n")
        self._emit({"name": "process_name", "ph": "M", "pid": rank,
                    "args": {"name": f"rank {rank}"}})

    def _emit(self, ev):
        # comma BEFORE every event but the first: the array never carries
        # a dangling comma, so the file is valid JSON the moment the
        # closing bracket lands
        prefix = "" if self._first else ",\n"
        self._first = False
        self._f.write(prefix + json.dumps(ev))

    def _lane(self, tensor: str) -> int:
        if tensor not in self._lanes:
            tid = len(self._lanes) + 1
            self._lanes[tensor] = tid
            self._emit({"name": "thread_name", "ph": "M", "pid": self._rank,
                        "tid": tid, "args": {"name": tensor}})
        return self._lanes[tensor]

    def now_us(self) -> int:
        return int((time.perf_counter() - self._t0) * 1e6)

    def record(self, tensor: str, activity: str, phase: str, dur_us: int = 0,
               ts_us: int = -1):
        ts = self.now_us() if ts_us < 0 else ts_us
        with self._lock:
            if self._closed:
                return
            tid = self._lane(tensor)
            ev = {"name": activity, "cat": "bluefog", "ph": phase, "ts": ts,
                  "pid": self._rank, "tid": tid}
            if phase == "X":
                ev["dur"] = dur_us
            if phase == "i":
                ev["s"] = "t"
            self._emit(ev)

    def counter(self, name: str, value: float, series: str = "value",
                ts_us: int = -1):
        """Chrome-tracing counter event (``"ph":"C"``): renders as a graph
        lane named ``name`` with one series per ``args`` key.  Non-finite
        samples are clamped/dropped (the strict-JSON guarantee holds even
        when training diverges)."""
        value = _finite_counter_value(value)
        if value is None:
            return
        ts = self.now_us() if ts_us < 0 else ts_us
        with self._lock:
            if self._closed:
                return
            self._emit({"name": name, "cat": "bluefog", "ph": "C", "ts": ts,
                        "pid": self._rank, "args": {series: value}})

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._emit({"name": "timeline_closed", "ph": "i",
                        "pid": self._rank, "tid": 0, "ts": self.now_us(),
                        "s": "g"})
            self._f.write("\n]\n")
            self._f.close()


class _Timeline:
    def __init__(self):
        self._native = None
        self._py: Optional[_PyWriter] = None
        self._path: Optional[str] = None
        self._session = 0  # bumps on every start(); stamps span tokens

    @property
    def enabled(self) -> bool:
        return self._native is not None or self._py is not None

    def start(self, file_prefix: str, rank: int) -> str:
        if self.enabled:
            raise RuntimeError("timeline already started; call timeline_end() first")
        path = f"{file_prefix}{rank}.json"
        self._session += 1
        lib = native.load()
        if lib is not None and lib.bft_timeline_open(path.encode(), rank) == 0:
            self._native = lib
        else:
            self._py = _PyWriter(path, rank)
        self._path = path
        return path

    def end(self):
        if self._native is not None:
            self._native.bft_timeline_close()
            self._native = None
        if self._py is not None:
            self._py.close()
            self._py = None
        self._path = None

    def record(self, tensor: str, activity: str, phase: str, dur_us: int = 0,
               ts_us: int = -1):
        if self._native is not None:
            self._native.bft_timeline_record_at(
                tensor.encode(), activity.encode(), phase.encode(), ts_us,
                dur_us)
        elif self._py is not None:
            self._py.record(tensor, activity, phase, dur_us, ts_us)

    def counter(self, name: str, value: float, series: str = "value",
                ts_us: int = -1):
        # sanitize HERE for the native path too: csrc's %.17g would print
        # 'nan'/'inf', which no JSON parser accepts (the Python writer
        # sanitizes again for direct _PyWriter users)
        value = _finite_counter_value(value)
        if value is None:
            return
        if self._native is not None:
            self._native.bft_timeline_counter(
                name.encode(), series.encode(), value, ts_us)
        elif self._py is not None:
            self._py.counter(name, value, series, ts_us)

    def now_us(self) -> int:
        if self._native is not None:
            return int(self._native.bft_timeline_now_us())
        if self._py is not None:
            return self._py.now_us()
        return 0


_timeline = _Timeline()


def timeline_enabled() -> bool:
    return _timeline.enabled


def timeline_start(file_prefix: Optional[str] = None,
                   rank: Optional[int] = None) -> Optional[str]:
    """Open the per-rank timeline file (reference basics.py:456-480).

    Called automatically by ``bf.init()`` when ``BLUEFOG_TIMELINE`` is set.
    """
    if file_prefix is None:
        file_prefix = os.environ.get(_ENV)
    if not file_prefix:
        return None
    if rank is None:
        from . import context as _ctx
        rank = _ctx.ctx().rank() if _ctx.is_initialized() else 0
    return _timeline.start(file_prefix, rank)


def timeline_end():
    _timeline.end()


atexit.register(timeline_end)


def timeline_start_activity(tensor_name: str, activity_name: str) -> bool:
    """Begin a user activity on the named lane (reference basics.py:482-516)."""
    if not _timeline.enabled:
        return False
    _timeline.record(tensor_name, activity_name, "B")
    return True


def timeline_end_activity(tensor_name: str) -> bool:
    if not _timeline.enabled:
        return False
    _timeline.record(tensor_name, "", "E")
    return True


@contextmanager
def timeline_context(tensor_name: str, activity_name: str):
    """``with bf.timeline_context("tensor", "COMPUTE"): ...``
    (reference basics.py:518-546)."""
    timeline_start_activity(tensor_name, activity_name)
    try:
        import jax
        with jax.named_scope(activity_name):
            yield
    finally:
        timeline_end_activity(tensor_name)


# -- op-layer hooks ---------------------------------------------------------

def record_op_phase(name: str, activity: str, phase: str = "i"):
    """Lightweight hook used by the op layer; no-op unless enabled."""
    if _timeline.enabled:
        _timeline.record(name, activity, phase)


def op_start_us():
    """Opaque token for a later :func:`record_op_span`; None when disabled.
    The token carries the timeline session id so spans never straddle a
    timeline restart (which would corrupt timestamps)."""
    if not _timeline.enabled:
        return None
    return (_timeline._session, _timeline.now_us())


def record_op_span(name: str, activity: str, token):
    """Emit a complete ('X') span from the token's timestamp to now.  Used
    for the async COMMUNICATE window so handles that are polled or abandoned
    never leave an unclosed begin event in the trace.  Tokens minted while
    the timeline was disabled or during a previous session are dropped."""
    if token is None or not _timeline.enabled:
        return
    session, start_us = token
    if session != _timeline._session:
        return
    end = _timeline.now_us()
    _timeline.record(name, activity, "X", max(0, end - start_us), start_us)


# the lane every step loop stamps its per-round sync spans on — the
# cross-rank matching key the fleet trace merger aligns clocks with
GOSSIP_LANE = "gossip"


def record_gossip_round(step, token):
    """Close a ``round <step>`` span on the :data:`GOSSIP_LANE`.

    Stamped by the optimizer step loops around each exchange-bearing
    step: a gossip round is a collective, so every participating rank
    finishes round *k* together — which makes these spans the clock-sync
    anchors ``bftrace`` (``observability/tracemerge.py``) matches across
    per-rank trace files to estimate per-rank clock offsets, and the
    endpoints its cross-rank flow arrows attach to.  ``step`` must be a
    host int (the loop index, not a traced array); token from
    :func:`op_start_us`.  No-op while the timeline is disabled."""
    record_op_span(GOSSIP_LANE, f"round {int(step)}", token)


def record_counter(name: str, value: float, series: str = "value",
                   ts_us: int = -1):
    """Emit a Chrome-tracing counter sample (``"ph":"C"``) — Perfetto
    renders each distinct ``name`` as a live graph lane next to the op
    spans.  The observability exporter mirrors per-step telemetry through
    here (``observability/export.py::log_step``); call it directly for
    custom lanes.  No-op unless the timeline is enabled."""
    if _timeline.enabled:
        _timeline.counter(name, value, series, ts_us)


def record_resilience_event(kind: str, detail: str = ""):
    """Fault/repair instant on the dedicated ``resilience`` lane: chaos-run
    boundaries, fault onsets, membership confirmations, matrix repairs.
    Counted in the host metrics registry when that is enabled
    (``bf_resilience_events_total{kind=...}``); the timeline instant is
    emitted only while a timeline is open (like every host activity)."""
    from .observability import metrics as _metrics
    if _metrics.enabled():
        _metrics.counter(
            "bf_resilience_events_total",
            "resilience events by kind (fault onsets, degradations, "
            "confirmations, repairs, chaos-run boundaries)").inc(kind=kind)
    if _timeline.enabled:
        name = f"{kind}: {detail}" if detail else kind
        _timeline.record("resilience", name, "i")


@contextmanager
def op_phase(name: str, activity: str):
    if not _timeline.enabled:
        yield
        return
    _timeline.record(name, activity, "B")
    try:
        yield
    finally:
        _timeline.record(name, "", "E")
