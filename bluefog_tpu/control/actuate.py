"""Actuation layer: apply controller decisions through recompile-free
channels only.

The whole design constraint (ROADMAP "closed-loop adaptive controller")
is that adaptation must never churn the step cache: every actuated knob
is either TRACED DATA the compiled program already consumes, or a value
already folded into ``optim/_plumbing.step_cache_key`` whose programs
were built up front.  Two channels exist:

* **Schedule mode** — a :class:`SwitchableSchedule` stacks the candidate
  mixing schedules (static matrix, one-peer dynamic exponential,
  cost-reweighted static) into ONE compiled
  :class:`~..parallel.schedule.DynamicSchedule` whose period covers
  every mode; the mode is selected by remapping the step index the
  jitted program receives (``virtual_step``) — the step index is traced
  data, so switching modes is a pure host-side integer change.  Zero
  recompiles, asserted by ``tests/test_control.py``.
* **CHOCO γ scale** — a float32 scalar riding the carried compression
  state (``compress/exchange.py`` reads ``state["gamma_scale"]``), so
  backing off / re-arming the consensus stepsize is a traced-value
  change.  The optimizer wrapper injects the current value each step
  when built with ``control=True`` (``BLUEFOG_CONTROL=on``).

The :class:`Actuator` holds the live knob values and implements the
optimizer's controller-hook protocol (``graph_step`` / ``after_step``),
so it can be attached directly for tests; the full sensing loop lives in
:class:`~.controller.Controller`.
"""

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..parallel import dynamic as _dyn
from ..parallel.schedule import (
    DynamicSchedule,
    compile_dynamic_matrices,
)
from ..parallel.schedule_ir import (
    ScheduleIR,
    ir_from_matrices,
    ir_from_matrix,
)
from . import policy as _policy

__all__ = [
    "SwitchableSchedule", "build_switchable_schedule",
    "reweight_matrix_by_cost", "Actuator",
]


@dataclasses.dataclass(frozen=True, eq=False)
class SwitchableSchedule:
    """Several mixing schedules compiled into one fixed-shape program.

    ``sched`` is a plain :class:`DynamicSchedule` of period
    ``n_modes * base_period`` whose weight tables hold mode m's step-t
    matrix at row ``m * base_period + t``; the offset superset is the
    union over modes, so every mode runs through the SAME compiled
    collective schedule (absent edges simply carry zero weight).  Pass
    ``sched`` to the optimizer (``sched=sw.sched``) and feed it
    ``virtual_step(step, mode)`` as the step index — the controller's
    mode knob is then pure traced data."""

    sched: DynamicSchedule
    mode_names: Tuple[str, ...]
    base_period: int

    def mode_index(self, name: str) -> int:
        try:
            return self.mode_names.index(name)
        except ValueError:
            raise ValueError(
                f"unknown schedule mode {name!r} "
                f"(have {list(self.mode_names)})") from None

    def virtual_step(self, step: int, mode: int) -> int:
        """Host-side step remap selecting ``mode``'s table rows: the
        jitted program computes ``vstep % period`` with ``period =
        n_modes * base_period``, so row ``mode * T + step % T`` is
        exactly mode's step-t matrix."""
        return int(mode) * self.base_period + int(step) % self.base_period

    def matrices_for(self, name: str) -> np.ndarray:
        """Mode ``name``'s ``[T, N, N]`` matrix stack (reference/tests)."""
        m = self.mode_index(name)
        lo = m * self.base_period
        return self.sched.matrices[lo:lo + self.base_period]


def reweight_matrix_by_cost(W: np.ndarray, cost, alpha: float = 1.0
                            ) -> np.ndarray:
    """Reweight a column-stochastic mixing matrix by MEASURED edge costs
    (arXiv:2309.13541: exchange schedules should follow the real link
    model, not the nominal graph).

    Each off-diagonal ``W[i, j]`` is scaled by ``(median_latency /
    latency(i -> j)) ** alpha`` — slow edges lose mixing weight, fast
    edges gain it — then every column is renormalized to sum to 1
    (receiver j's average stays an average; column-stochasticity, the
    mass-conservation invariant every compiled topology here satisfies,
    is preserved exactly).  ``cost`` is an
    :class:`~..observability.commprof.EdgeCostMatrix`."""
    W = np.asarray(W, dtype=np.float64).copy()
    n = W.shape[0]
    lats = {}
    for i in range(n):
        for j in range(n):
            if i != j and W[i, j] != 0:
                lat = cost.latency_us(i, j)
                if lat is not None and math.isfinite(lat) and lat > 0:
                    lats[(i, j)] = lat
    if not lats:
        return W
    med = sorted(lats.values())[len(lats) // 2]
    if med <= 0:
        return W
    for (i, j), lat in lats.items():
        W[i, j] *= (med / lat) ** alpha
    col = W.sum(axis=0)
    col[col == 0] = 1.0
    return W / col[None, :]


def _digraph_of(topo):
    """The networkx digraph of a compiled topology (reconstructed from
    the weight matrix when the topology was compiled from a raw W)."""
    import networkx as nx
    if topo.digraph is not None:
        return topo.digraph
    W = np.asarray(topo.weight_matrix)
    g = nx.DiGraph()
    g.add_nodes_from(range(W.shape[0]))
    for s, d in zip(*np.nonzero(W)):
        if s != d:
            g.add_edge(int(s), int(d))
    return g


def build_switchable_schedule(topo=None, *,
                              static_matrix: Optional[np.ndarray] = None,
                              factory=None,
                              period: Optional[int] = None,
                              cost_matrix=None,
                              cost_alpha: float = 1.0,
                              synthesized: Optional[ScheduleIR] = None,
                              max_period: int = 4096
                              ) -> SwitchableSchedule:
    """Compile the controller's schedule modes into one
    :class:`SwitchableSchedule`.

    Every mode is built as a
    :class:`~..parallel.schedule_ir.ScheduleIR` first — one
    construction path for hand-built and synthesized schedules alike —
    then tiled to the shared base period and lowered together.  Modes
    (in index order):

    * ``"static"``  — ``static_matrix`` (default: ``topo``'s compiled
      weight matrix) repeated every step;
    * ``"dynamic"`` — the one-peer dynamic schedule from ``factory``
      (default: ``GetDynamicOnePeerSendRecvRanks`` over ``topo``'s
      digraph — the O(1)-degree rotation of arXiv:2110.13363);
    * ``"cost"``    — ``static_matrix`` reweighted by the measured
      ``cost_matrix`` (:func:`reweight_matrix_by_cost`); only present
      when a matrix is supplied.  Callers must gate the matrix with
      ``commprof.matrix_is_usable`` first — a synthetic or stale matrix
      must not become a link model.
    * ``"synthesized"`` — a pre-built IR (``control.synthesize``); only
      present when supplied.  Its period folds into the base period by
      least common multiple, so hot-swapping between it and the
      fallback modes stays a pure virtual-step remap.

    ``topo`` defaults to the current context's compiled topology."""
    if topo is None:
        from ..context import ctx
        topo = ctx().compiled_topology
    W = (np.asarray(static_matrix, np.float64) if static_matrix is not None
         else np.asarray(topo.weight_matrix, np.float64))
    n = W.shape[0]
    if factory is None:
        factory = _dyn.one_peer_factory(_digraph_of(topo))
    if period is None:
        period = _dyn.schedule_period(factory, n, max_period=max_period)
    dyn_mats = _dyn.dynamic_mixing_matrices(factory, n, period)
    irs = [ir_from_matrix(W, name="static"),
           ir_from_matrices(dyn_mats, name="dynamic")]
    names = ["static", "dynamic"]
    if cost_matrix is not None:
        cost_W = reweight_matrix_by_cost(W, cost_matrix, cost_alpha)
        irs.append(ir_from_matrix(cost_W, name="cost"))
        names.append("cost")
    if synthesized is not None:
        if synthesized.size != n:
            raise ValueError(
                f"synthesized schedule is for {synthesized.size} ranks, "
                f"fleet has {n}")
        irs.append(synthesized)
        names.append("synthesized")
    base_period = period
    for ir in irs:
        base_period = math.lcm(base_period, ir.period)
    if base_period > max_period:
        raise ValueError(
            f"combined mode period {base_period} exceeds max_period "
            f"{max_period}")
    stacks = [ir.tile(base_period) for ir in irs]
    sched = compile_dynamic_matrices(np.concatenate(stacks, axis=0))
    return SwitchableSchedule(sched=sched, mode_names=tuple(names),
                              base_period=base_period)


class Actuator:
    """Applies :class:`~.policy.Decision` records to one optimizer.

    Implements the optimizer controller-hook protocol
    (``graph_step``/``after_step``) so it can be attached directly
    (``opt.attach_controller(actuator)``) — the compile-count test
    drives interventions this way without the sensing loop.  In
    ``shadow`` mode :meth:`apply` records but never moves a knob."""

    def __init__(self, optimizer, *,
                 schedule: Optional[SwitchableSchedule] = None,
                 mode: Optional[str] = None,
                 initial_mode: Optional[str] = None,
                 cadence=None):
        self.opt = optimizer
        self.schedule = schedule
        self.cadence = cadence          # CadenceScheduler (async runs)
        self.mode = _policy.control_mode(mode)
        if schedule is not None:
            name = initial_mode or schedule.mode_names[0]
            self.sched_mode = schedule.mode_index(name)
        else:
            self.sched_mode = 0
        cfg = getattr(optimizer, "compression", None)
        self.gamma_knob = bool(cfg is not None and getattr(cfg, "choco",
                                                          False))

    # -- optimizer hook protocol --------------------------------------------

    def graph_step(self, step: int) -> int:
        if self.schedule is None:
            return int(step)
        return self.schedule.virtual_step(step, self.sched_mode)

    def after_step(self, step: int) -> None:
        """No sensing here — the Controller subclasses the loop."""

    # -- knobs --------------------------------------------------------------

    @property
    def mode_name(self) -> Optional[str]:
        if self.schedule is None:
            return None
        return self.schedule.mode_names[self.sched_mode]

    @property
    def gamma_scale(self) -> float:
        knobs = getattr(self.opt, "control_knobs", None)
        return float(knobs.get("gamma_scale", 1.0)) if knobs else 1.0

    def available_modes(self) -> Tuple[str, ...]:
        return self.schedule.mode_names if self.schedule else ()

    def apply(self, decision: _policy.Decision) -> bool:
        """Actuate one decision.  Returns True when a knob actually
        moved (always False in shadow mode — the audit-trail contract)."""
        if self.mode != "on":
            return False
        if decision.knob == "schedule" and self.schedule is not None:
            self.sched_mode = self.schedule.mode_index(str(decision.value))
            return True
        if decision.knob == "gamma" and self.gamma_knob:
            knobs = getattr(self.opt, "control_knobs", None)
            # the optimizer must have the γ leaf PLUMBED (built with
            # control=True): writing the knob of an unplumbed optimizer
            # would log applied:true for an intervention the traced
            # program never sees — the trail must stay truthful
            if knobs is None or not getattr(self.opt, "_gamma_plumbed",
                                            False):
                return False
            knobs["gamma_scale"] = float(decision.value)
            return True
        if decision.knob == "cadence" and self.cadence is not None:
            rank, period = decision.value
            self.cadence.set_period(int(rank), int(period))
            return True
        return False
