"""Generative schedule synthesis: bandwidth-optimal exchange schedules
from the MEASURED fabric.

The PR 9 controller *selects* among hand-built schedule modes; this
module *generates* one.  Given a usable
:class:`~..observability.commprof.EdgeCostMatrix` (gated through the
same ``matrix_is_usable`` guard the controller applies to every sensing
artifact), :func:`synthesize_schedule` emits a multi-round
:class:`~..parallel.schedule_ir.ScheduleIR` that minimizes the
per-round **bottleneck-edge cost** — per arXiv:2309.13541, schedules
fitted to the measured direct-connect topology cut exchange time well
below topology-oblivious rings — subject to the repo's matrix
invariants (non-negativity, column-stochasticity, spectral-gap floor on
the period product; ``schedule_ir.check_schedule_invariants``).

The synthesis is deterministic greedy:

1. price every measured directed edge by its largest-payload latency;
2. keep the cheapest prefix whose union is strongly connected (the
   minimum requirement for the period product to mix at all), then
   extend with every edge within ``slack`` × the prefix bottleneck
   (cheap extra edges improve the gap for free);
3. pack the kept edges into rounds that are partial permutations —
   at most one send and one receive per rank per round, so each round
   is a true one-shot exchange and the round's cost is its slowest
   edge, not a serialization artifact;
4. weight each round by the repo's one-peer convention
   (``1 / (in_degree + 1)``, shared with the self loop) and validate;
   if the spectral-gap floor fails, admit the next-cheapest measured
   edges and retry.

When the matrix is refused (foreign platform, stale artifact, missing)
or the fleet is degraded, :func:`synthesize_or_fallback` returns the
O(1)-degree one-peer exponential family instead (arXiv:2110.13363) —
provably convergent with zero fabric knowledge.

Knobs (``BLUEFOG_SCHED_*``, docs/env_variable.md):
``BLUEFOG_SCHED_MAX_ROUNDS``, ``BLUEFOG_SCHED_GAP_FLOOR``,
``BLUEFOG_SCHED_SLACK``.
"""

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import networkx as nx

from ..parallel import dynamic as _dyn
from ..parallel.schedule_ir import (
    ScheduleIR,
    check_schedule_invariants,
    ir_from_matrices,
    ir_from_one_peer,
)

__all__ = [
    "SynthesisConfig", "synthesize_schedule", "fallback_schedule_ir",
    "synthesize_or_fallback", "predicted_round_costs",
    "predicted_bottleneck_us", "write_schedule_record",
]

_ENV_PREFIX = "BLUEFOG_SCHED_"


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(_ENV_PREFIX + name)
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(_ENV_PREFIX + name)
    return int(v) if v else default


@dataclasses.dataclass(frozen=True)
class SynthesisConfig:
    """Synthesizer knobs (env defaults: ``BLUEFOG_SCHED_*``).

    * ``max_rounds`` — cap on the schedule period: edges that cannot be
      packed within this many partial-permutation rounds are dropped
      (connectivity-critical edges raise instead);
    * ``gap_floor`` — required spectral gap of the period product;
    * ``slack`` — edges within ``slack ×`` the connectivity bottleneck
      latency are admitted beyond the minimal strongly-connected core.
    """

    max_rounds: int = 16
    gap_floor: float = 1e-3
    slack: float = 1.25

    @classmethod
    def from_env(cls) -> "SynthesisConfig":
        return cls(
            max_rounds=_env_int("MAX_ROUNDS", cls.max_rounds),
            gap_floor=_env_float("GAP_FLOOR", cls.gap_floor),
            slack=_env_float("SLACK", cls.slack),
        )


def _edge_latencies(matrix) -> Dict[Tuple[int, int], float]:
    """Largest-payload latency per measured directed edge (µs)."""
    lats: Dict[Tuple[int, int], float] = {}
    for e in matrix.entries:
        src, dst = int(e["src"]), int(e["dst"])
        if src == dst:
            continue
        lat = matrix.latency_us(src, dst)
        if lat is not None and np.isfinite(lat) and lat > 0:
            lats[(src, dst)] = float(lat)
    return lats


def _strongly_connected(n: int, edges: Sequence[Tuple[int, int]]) -> bool:
    g = nx.DiGraph()
    g.add_nodes_from(range(n))
    g.add_edges_from(edges)
    return nx.is_strongly_connected(g)


def _pack_rounds(n: int, edges: Sequence[Tuple[int, int]],
                 core: frozenset, max_rounds: int
                 ) -> List[List[Tuple[int, int]]]:
    """First-fit edges into partial-permutation rounds (≤ 1 send and
    ≤ 1 receive per rank per round).  Core (connectivity-critical)
    edges that cannot be placed raise; slack edges are dropped."""
    rounds: List[List[Tuple[int, int]]] = []
    out_used: List[set] = []
    in_used: List[set] = []
    for (s, d) in edges:
        placed = False
        for k in range(len(rounds)):
            if s not in out_used[k] and d not in in_used[k]:
                rounds[k].append((s, d))
                out_used[k].add(s)
                in_used[k].add(d)
                placed = True
                break
        if not placed:
            if len(rounds) < max_rounds:
                rounds.append([(s, d)])
                out_used.append({s})
                in_used.append({d})
            elif (s, d) in core:
                raise ValueError(
                    f"cannot pack connectivity-critical edge {s}->{d} "
                    f"within max_rounds={max_rounds}")
    return rounds


def synthesize_schedule(matrix, cfg: Optional[SynthesisConfig] = None,
                        name: str = "synthesized") -> ScheduleIR:
    """Synthesize a bottleneck-minimizing schedule from a measured
    :class:`~..observability.commprof.EdgeCostMatrix`.

    Callers must gate ``matrix`` through ``commprof.matrix_is_usable``
    first (or use :func:`synthesize_or_fallback`, which does) — a
    foreign-platform or stale matrix must not become a link model.
    Raises ``ValueError`` when the measured edges cannot form a valid
    schedule (not strongly connected, or gap floor unreachable).
    """
    cfg = cfg or SynthesisConfig.from_env()
    n = int(matrix.n)
    lats = _edge_latencies(matrix)
    ordered = sorted(lats, key=lambda e: (lats[e], e))
    if not _strongly_connected(n, ordered):
        raise ValueError(
            f"measured edges do not strongly connect all {n} ranks — "
            "cannot synthesize a mixing schedule")

    # minimal cheap prefix that strongly connects the fleet
    lo, hi = 1, len(ordered)
    while lo < hi:
        mid = (lo + hi) // 2
        if _strongly_connected(n, ordered[:mid]):
            hi = mid
        else:
            lo = mid + 1
    k0 = lo
    core = frozenset(ordered[:k0])
    bottleneck = lats[ordered[k0 - 1]]
    k = k0
    while k < len(ordered) and lats[ordered[k]] <= cfg.slack * bottleneck:
        k += 1

    last_err = None
    while True:
        chosen = ordered[:k]
        packed = _pack_rounds(n, chosen, core, cfg.max_rounds)
        mats = []
        for rnd in packed:
            sends: List[List[int]] = [[] for _ in range(n)]
            for s, d in rnd:
                sends[s].append(d)
            mats.append(_dyn.dynamic_mixing_matrix(n, sends))
        ir = ir_from_matrices(np.stack(mats), name=name)
        try:
            check_schedule_invariants(ir, gap_floor=cfg.gap_floor)
            return ir
        except ValueError as e:
            last_err = e
            if k >= len(ordered):
                raise ValueError(
                    f"no schedule over the measured edges reaches the "
                    f"spectral-gap floor {cfg.gap_floor:g}: {last_err}"
                ) from None
            k += 1  # admit the next-cheapest measured edge and retry


def fallback_schedule_ir(topo=None, max_period: int = 4096) -> ScheduleIR:
    """The one-peer exponential fallback over the nominal topology
    (arXiv:2110.13363) — used whenever the measured matrix is refused
    or the fleet is degraded.  ``topo`` defaults to the current
    context's compiled topology."""
    from .actuate import _digraph_of
    if topo is None:
        from ..context import ctx
        topo = ctx().compiled_topology
    return ir_from_one_peer(_digraph_of(topo), max_period=max_period,
                            name="fallback_one_peer")


def synthesize_or_fallback(matrix, topo=None, *,
                           platform: Optional[str] = None,
                           path: Optional[str] = None,
                           cfg: Optional[SynthesisConfig] = None,
                           degraded: bool = False
                           ) -> Tuple[ScheduleIR, str, str]:
    """The gated entry point: ``(ir, source, reason)``.

    ``source`` is ``"synthesized"`` when the matrix passed
    ``matrix_is_usable`` and synthesis succeeded, else ``"fallback"``
    with ``reason`` naming the refusal (the same strings the
    controller's artifact gate logs)."""
    from ..observability import commprof as _commprof
    if degraded:
        return fallback_schedule_ir(topo), "fallback", "fleet degraded"
    if matrix is None:
        return fallback_schedule_ir(topo), "fallback", "no cost matrix"
    ok, why = _commprof.matrix_is_usable(matrix, path=path,
                                         platform=platform)
    if not ok:
        return fallback_schedule_ir(topo), "fallback", why
    try:
        return synthesize_schedule(matrix, cfg=cfg), "synthesized", ""
    except ValueError as e:
        return fallback_schedule_ir(topo), "fallback", str(e)


# ---------------------------------------------------------------------------
# Cost prediction (the bench-schedule evidence)
# ---------------------------------------------------------------------------

def predicted_round_costs(ir: ScheduleIR, matrix) -> List[float]:
    """Per-round bottleneck-edge cost (µs) under the measured matrix.

    A round's edges fire concurrently (partial permutation → one
    ppermute family), so its cost is its SLOWEST edge; unmeasured edges
    price at 0 (they contribute no measured evidence either way)."""
    costs = []
    for r in ir.rounds:
        worst = 0.0
        for s, d, _ in r.edges:
            lat = matrix.latency_us(s, d)
            if lat is not None and np.isfinite(lat):
                worst = max(worst, float(lat))
        costs.append(worst)
    return costs


def predicted_bottleneck_us(ir: ScheduleIR, matrix) -> float:
    """The schedule's bottleneck round cost — the quantity synthesis
    minimizes and ``make bench-schedule`` compares against the static
    ring."""
    costs = predicted_round_costs(ir, matrix)
    return max(costs) if costs else 0.0


# ---------------------------------------------------------------------------
# Decision-trail record
# ---------------------------------------------------------------------------

def write_schedule_record(path: str, ir: ScheduleIR, *,
                          step: Optional[int] = None,
                          source: str = "synthesized",
                          reason: str = "",
                          matrix=None) -> dict:
    """Append one ``kind: "schedule"`` record to a decision trail.

    The record carries the schedule's identity (fingerprint), shape
    (period, offset superset, per-round edges) and — when the pricing
    matrix is at hand — the predicted per-round costs, so a trail
    replay can reconstruct WHY the controller armed this schedule.
    Size-bounded by the same ``BLUEFOG_METRICS_MAX_MB`` rotation as
    every other JSONL sink."""
    from ..observability import export as _export
    max_bytes, keep = _export.resolve_rotation()
    if max_bytes:
        try:
            if os.path.getsize(path) >= max_bytes:
                _export.rotate_file(path, keep)
        except OSError:
            pass
    rec = {
        "kind": "schedule",
        "t_us": int(time.time() * 1e6),
        "source": str(source),
        "fingerprint": ir.fingerprint(),
        "period": ir.period,
        "size": ir.size,
        "name": ir.name,
        "offsets": list(ir.offsets()),
        "rounds": [{"edges": [[s, d, w] for s, d, w in r.edges]}
                   for r in ir.rounds],
    }
    if step is not None:
        rec["step"] = int(step)
    if reason:
        rec["reason"] = str(reason)
    if matrix is not None:
        costs = predicted_round_costs(ir, matrix)
        rec["round_costs_us"] = costs
        rec["bottleneck_us"] = max(costs) if costs else 0.0
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec
