"""Closed-loop adaptive control: telemetry in, runtime knob decisions out.

PRs 4-8 built the sensing stack (in-graph telemetry, the fleet health
engine, the measured edge-cost matrix and overlap efficiency); this
package closes the loop — a host-side feedback controller that turns
those signals into runtime topology/schedule/compression decisions, and
actuates them ONLY through channels that are traced data (the step
index selecting a :class:`SwitchableSchedule` mode, the CHOCO γ scale
riding the compression state), so adaptation never recompiles the step.

Layers (docs/control.md):

* :mod:`~.policy`   — the deterministic decision engine: health
  verdicts + residual margins + measured link costs -> ``Decision``
  records, with hysteresis and per-knob cooldowns.
* :mod:`~.actuate`  — :class:`SwitchableSchedule` (pre-compiled mode
  stack) and the :class:`Actuator` applying decisions to an optimizer.
* :mod:`~.controller` — the :class:`Controller` facade wiring the
  sensing loop into the optimizer's step hook and appending the
  decision JSONL trail ``bfmonitor`` renders and ``bfctl replay``
  reproduces.

Modes (``BLUEFOG_CONTROL``): ``off`` (default — the controller is
inert), ``shadow`` (full sensing + policy, decisions logged with
``applied: false``, nothing actuated — the audit trail to trust before
enabling), ``on`` (actuate).
"""

from .policy import (
    CONTROL_ENV,
    ControlConfig,
    Decision,
    PolicyEngine,
    control_mode,
    read_decisions,
    slow_edge,
)
from .actuate import (
    Actuator,
    SwitchableSchedule,
    build_switchable_schedule,
    reweight_matrix_by_cost,
)
from .controller import Controller, DECISIONS_SUFFIX
from .synthesize import (
    SynthesisConfig,
    fallback_schedule_ir,
    predicted_bottleneck_us,
    predicted_round_costs,
    synthesize_or_fallback,
    synthesize_schedule,
    write_schedule_record,
)

__all__ = [
    "CONTROL_ENV", "ControlConfig", "Decision", "PolicyEngine",
    "control_mode", "read_decisions", "slow_edge",
    "Actuator", "SwitchableSchedule", "build_switchable_schedule",
    "reweight_matrix_by_cost", "Controller", "DECISIONS_SUFFIX",
    "SynthesisConfig", "synthesize_schedule", "synthesize_or_fallback",
    "fallback_schedule_ir", "predicted_round_costs",
    "predicted_bottleneck_us", "write_schedule_record",
]
