"""The closed-loop controller: sensing -> policy -> actuation per step.

Attach one to an optimizer and the loop runs itself from inside the
optimizer's step hook::

    sw = control.build_switchable_schedule(cost_matrix=usable_matrix)
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1), sched=sw.sched, telemetry=True, control=True)
    ctl = control.Controller(opt, schedule=sw, prefix="/tmp/series_")
    ...
    params, state, snap = opt.step(params, grads, state, t)  # hook fires
    export.log_step(t, snap)

Every ``cfg.every`` steps the controller loads the fleet view from the
JSONL series the run is writing (``observability/aggregate.load_fleet``
with a tail cache — only appended bytes are parsed), evaluates the
health engine, feeds verdicts + the measured edge costs to the
:class:`~.policy.PolicyEngine`, applies the resulting decisions through
the :class:`~.actuate.Actuator` (``on`` mode only), and appends them to
the decision JSONL (``<prefix>decisions.jsonl``) that ``bfmonitor``
renders and ``bfctl replay`` reproduces.

Sensing-artifact hygiene: an ``edges_artifact`` path is loaded ONCE and
gated through ``commprof.matrix_is_usable`` — a matrix probed on a
different backend (``platform`` mismatch) or written before this run
started (stale mtime) is refused with a counter
(``bf_control_refused_matrix_total``) instead of silently becoming a
link model.  Edge records riding the telemetry JSONL carry their
``edges_platform`` and are gated the same way — as are edge rows that
arrived over the fabric: ``evaluate_plane`` senses from the in-band
telemetry plane's local view, and its plane-gossiped matrix passes the
identical ``matrix_is_usable`` gate with plane age as the freshness
bound (docs/observability.md "In-band telemetry plane").

Because the hook runs INSIDE ``opt.step(t)`` — before the caller logs
step t — an evaluation at step t sees records ``<= t-1``.  ``bfctl
replay`` applies the same cutoff, which is what makes the live and
replayed trails identical.
"""

import os
import time
from typing import Optional

from ..observability import aggregate as AG
from ..observability import health as H
from ..observability import metrics as _metrics
from . import actuate as _actuate
from . import policy as _policy

__all__ = ["Controller"]

DECISIONS_SUFFIX = "decisions.jsonl"

_MODE_GAUGE = {"off": 0.0, "shadow": 1.0, "on": 2.0}


class Controller(_actuate.Actuator):
    """Sensing + policy + actuation, attached to one optimizer."""

    def __init__(self, optimizer, *,
                 prefix: Optional[str] = None,
                 schedule: Optional[_actuate.SwitchableSchedule] = None,
                 config: Optional[_policy.ControlConfig] = None,
                 mode: Optional[str] = None,
                 initial_mode: Optional[str] = None,
                 decisions_path: Optional[str] = None,
                 expected_ranks: Optional[int] = None,
                 edges_artifact: Optional[str] = None,
                 health_config: Optional[H.HealthConfig] = None,
                 cadence=None,
                 attach: bool = True):
        super().__init__(optimizer, schedule=schedule, mode=mode,
                         initial_mode=initial_mode, cadence=cadence)
        self.cfg = config or _policy.ControlConfig.from_env()
        if prefix is None:
            from ..observability import export as _export
            path = _export.metrics_path()
            if path is not None:
                # strip the "<rank>.jsonl" tail of the open sink
                import re
                prefix = re.sub(r"\d+\.jsonl$", "", path)
            else:
                prefix = os.environ.get(_export.METRICS_ENV)
        self.prefix = prefix
        self.expected_ranks = expected_ranks
        self.decisions_path = decisions_path or (
            prefix + DECISIONS_SUFFIX if prefix else None)
        self.health_cfg = health_config or H.HealthConfig.from_env()
        if self.cfg.health_window:
            self.health_cfg.window = self.cfg.health_window
        self.engine = _policy.PolicyEngine(
            self.cfg, modes=self.available_modes(),
            initial_mode=self.mode_name, gamma=self.gamma_knob,
            cadence=cadence)
        self._cache = AG.TailCache()
        self._head = None               # built on the first decision
        self._platform = None           # resolved lazily (needs jax)
        self._artifact_entries = None
        self._artifact_checked = False
        self._edges_artifact = edges_artifact
        self.decisions = []             # every Decision this run emitted
        if attach and self.mode != "off":
            optimizer.attach_controller(self)
        self._mirror_gauges()

    # -- sensing ------------------------------------------------------------

    def _live_platform(self) -> Optional[str]:
        if self._platform is None:
            try:
                import jax
                self._platform = jax.default_backend()
            except Exception:
                self._platform = None
        return self._platform

    def _artifact(self):
        """The edge-artifact entries, gated once through
        ``matrix_is_usable`` (refusals counted, never retried — a stale
        file does not become fresh mid-run)."""
        if self._artifact_checked:
            return self._artifact_entries
        self._artifact_checked = True
        if not self._edges_artifact:
            return None
        from ..observability import commprof as CPROF
        try:
            matrix = CPROF.EdgeCostMatrix.load(self._edges_artifact)
        except (OSError, ValueError, KeyError) as e:
            self._refuse_matrix(f"unreadable artifact: {e}")
            return None
        ok, why = CPROF.matrix_is_usable(
            matrix, path=self._edges_artifact,
            platform=self._live_platform())
        if not ok:
            self._refuse_matrix(why)
            return None
        self._artifact_entries = matrix.entries
        return self._artifact_entries

    def _refuse_matrix(self, why: str) -> None:
        if _metrics.enabled():
            _metrics.counter(
                "bf_control_refused_matrix_total",
                "edge-cost matrices the controller refused to consume "
                "(foreign platform / stale mtime / unreadable)").inc()
        import logging
        logging.getLogger("bluefog").warning(
            "controller refused edge matrix: %s", why)

    def _plane_edges(self, view) -> Optional[list]:
        """Edge entries assembled from plane-gossiped rows, admitted
        through the SAME ``matrix_is_usable`` gate as a file artifact —
        platform must match the live backend, and the oldest live
        source's plane age is the freshness bound (fabric rows have no
        mtime)."""
        from ..observability import commprof as CPROF
        from ..observability import plane as PLANE
        matrix = PLANE.matrix_from_view(view)
        if matrix is None:
            return None
        ages = [m["age"] for m in view.per_source.values()
                if not m["stale"]]
        ok, why = CPROF.matrix_is_usable(
            matrix, platform=self._live_platform(),
            age_steps=max(ages, default=0))
        if not ok:
            self._refuse_matrix(why)
            return None
        return matrix.entries

    def _edges(self, view) -> Optional[list]:
        """Measured edge entries for the policy: the gated artifact
        first, then (on a plane-backed view) the plane-gossiped matrix,
        else the newest in-series record — gated on its recorded
        ``edges_platform`` the same way."""
        entries = self._artifact()
        if entries is not None:
            return entries
        if hasattr(view, "per_source"):
            entries = self._plane_edges(view)
            if entries is not None:
                return entries
        latest = view.latest_edges()
        if not latest:
            return None
        platform = latest.get("platform")
        live = self._live_platform()
        if platform is not None and live is not None and platform != live:
            self._refuse_matrix(
                f"in-series edges probed on {platform!r}, live backend "
                f"is {live!r}")
            return None
        return latest["entries"]

    # -- the per-step hook ---------------------------------------------------

    def after_step(self, step: int) -> None:
        step = int(step)
        if self.mode == "off" or self.prefix is None:
            return
        if step % self.cfg.every != self.cfg.every - 1:
            return
        view = AG.load_fleet(self.prefix,
                             expected_ranks=self.expected_ranks,
                             cache=self._cache)
        report = H.evaluate(view, self.health_cfg)
        self.evaluate_once(view, report, step)

    def evaluate_plane(self, view, step: Optional[int] = None) -> list:
        """One policy pass off the in-band telemetry plane's local
        fleet view (``observability.plane.FleetViewLive``) instead of
        JSONL files on disk — the multi-host sensing path: health is
        evaluated over the gossiped series, and plane-borne edge rows
        reach the policy through :meth:`_plane_edges`'s
        ``matrix_is_usable`` gate."""
        if step is None:
            step = view.plane_step
        report = H.evaluate(view, self.health_cfg)
        return self.evaluate_once(view, report, int(step))

    def evaluate_once(self, view, report, step: int) -> list:
        """One explicit policy pass (the hook's body; also the entry
        point for tests feeding synthetic views/reports)."""
        decisions = self.engine.evaluate(view, report, int(step),
                                         edges=self._edges(view))
        for d in decisions:
            d.mode = self.mode
            d.applied = self.apply(d)
            self.decisions.append(d)
            self._record(d)
        if decisions:
            self._mirror_gauges()
        return decisions

    # -- trail + gauges ------------------------------------------------------

    def _trail_header(self) -> dict:
        """The replayable ``control_config`` head record: engine
        identity PLUS everything else the live evaluation depended on —
        the full health config (a replay must not fall back to the
        replaying machine's ``BLUEFOG_HEALTH_*`` env), the expected
        fleet size, and the gated artifact entries when the controller
        consumed an edges artifact (they never ride the telemetry
        JSONL, so the trail itself must carry them)."""
        if self._head is None:
            import dataclasses
            head = self.engine.describe()
            head["every"] = self.cfg.every
            head["platform"] = self._live_platform()
            head["health"] = dataclasses.asdict(self.health_cfg)
            head["expected_ranks"] = self.expected_ranks
            if self._artifact_entries is not None:
                head["artifact_entries"] = self._artifact_entries
            self._head = head
        return self._head

    def _record(self, decision: _policy.Decision) -> None:
        if self.decisions_path:
            _policy.write_decision(self.decisions_path, decision,
                                   header=self._trail_header())
        if _metrics.enabled():
            _metrics.counter(
                "bf_control_decisions_total",
                "controller decisions by knob and action").inc(
                knob=decision.knob, action=decision.action)

    def _mirror_gauges(self) -> None:
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "bf_control_mode",
            "controller gate (0 off, 1 shadow, 2 on)").set(
            _MODE_GAUGE.get(self.mode, 0.0))
        _metrics.gauge(
            "bf_control_gamma_scale",
            "current CHOCO gamma scale the controller holds "
            "(1 = full rate)").set(self.engine.gamma_scale
                                   if self.mode != "on"
                                   else self.gamma_scale)
        if self.schedule is not None:
            _metrics.gauge(
                "bf_control_sched_mode",
                "current schedule mode index "
                "(SwitchableSchedule.mode_names order)").set(
                float(self.engine.mode_index_view()
                      if self.mode != "on" else self.sched_mode))
