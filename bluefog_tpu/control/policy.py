"""Closed-loop policy engine: health verdicts + measured link costs in,
structured :class:`Decision` records out.

This is the DECISION half of the ROADMAP's closed-loop adaptive
controller.  It consumes exactly the sensing endpoints earlier PRs
built — the fleet view (``observability/aggregate.load_fleet``), the
health engine's :class:`~..observability.health.HealthReport`, and the
comm profiler's measured :class:`~..observability.commprof.
EdgeCostMatrix` — and emits decisions over two runtime knobs the
actuation layer (``control/actuate.py``) can apply WITHOUT a recompile:

* ``schedule`` — which mode of a pre-compiled
  :class:`~.actuate.SwitchableSchedule` the exchange runs (static,
  one-peer dynamic exponential, cost-reweighted).  One-peer dynamic
  exponential graphs provably match static-graph convergence at O(1)
  degree (arXiv:2110.13363), so ``consensus_stall`` maps to
  ``switch -> dynamic``; exchange weights should follow the MEASURED
  link costs of the actual topology (arXiv:2309.13541), so a measured
  slow edge prefers the cost-reweighted mode once the fleet is healthy.
* ``gamma`` — a multiplicative scale on the CHOCO consensus stepsize
  (traced data riding the compression state, ``compress/exchange.py``).
  ``residual_blowup`` / a rising ‖residual‖/‖param‖ margin is the
  documented γ ≫ ω instability boundary (docs/compression.md
  "γ stability"): back γ off BEFORE the divergence step; re-arm toward
  full rate once consensus contracts again.
* ``cadence`` — a straggler-flagged rank's asynchronous gossip period
  (``async_train/cadence.py``'s :class:`CadenceScheduler`, a host-side
  table the traced program reads per step).  A ``straggler`` verdict
  lowers the flagged rank's cadence toward its measured slowdown ratio
  (never past the bounded-staleness cap); the verdict clearing restores
  the base period.

Determinism is a hard contract: decisions are a pure function of
(engine state, config, the recorded telemetry) — the live controller and
``bfctl replay`` over the same JSONL series produce the SAME trail, and
shadow vs on differ only in the ``mode``/``applied`` fields.  That is
what makes a shadow-mode audit trail trustworthy before anyone enables
actuation.

Stability machinery:

* **hysteresis** — backoff triggers at ``residual_high``; re-arm
  requires the margin BELOW the distinct ``residual_low`` floor plus
  ``rearm_after`` consecutive healthy evaluations, so the controller
  never chatters across one boundary.
* **per-knob cooldowns** — at most one decision per knob per
  ``cooldown`` steps; a persisting verdict does not machine-gun
  interventions.

Pure host-side stdlib (+ the numpy already inside the fleet view):
importing this module never touches JAX.
"""

import dataclasses
import json
import math
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CONTROL_ENV", "control_mode", "ControlConfig", "Decision",
    "PolicyEngine", "slow_edge", "read_decisions", "write_decision",
    "write_config_record", "DECISION_KEYS",
]

CONTROL_ENV = "BLUEFOG_CONTROL"

_MODES = ("off", "shadow", "on")

# every decision JSONL record carries at least these keys (the
# export.validate_jsonl contract for ``kind == "decision"`` lines)
DECISION_KEYS = ("step", "t_us", "knob", "action", "mode", "applied")


def control_mode(value: Optional[str] = None) -> str:
    """Resolve the controller gate: explicit argument wins, else
    ``BLUEFOG_CONTROL`` (default ``off``).  ``shadow`` runs the full
    sensing + policy loop and logs the decisions it WOULD take without
    actuating anything; ``on`` actuates."""
    if value is None:
        value = os.environ.get(CONTROL_ENV, "off")
    value = (value or "off").strip().lower()
    if value in ("", "0", "false", "none"):
        value = "off"
    if value == "1":
        value = "on"
    if value not in _MODES:
        raise ValueError(
            f"bad {CONTROL_ENV} value {value!r} (want off|shadow|on)")
    return value


_ENV_PREFIX = "BLUEFOG_CONTROL_"


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(_ENV_PREFIX + name)
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(_ENV_PREFIX + name)
    return int(v) if v else default


@dataclasses.dataclass
class ControlConfig:
    """Policy knobs (env defaults in parentheses; docs/control.md).

    ``every``           steps between policy evaluations (8)
    ``cooldown``        min steps between decisions PER KNOB (16)
    ``health_window``   health-rule window override (unset = the health
                        engine's own ``BLUEFOG_HEALTH_WINDOW``)
    ``gamma_backoff``   multiplicative γ-scale cut per backoff (0.5)
    ``gamma_floor``     γ-scale never drops below this (0.1)
    ``gamma_rearm``     γ-scale recovery multiplier per re-arm (2.0)
    ``residual_high``   backoff when the latest ‖residual‖/‖param‖
                        margin exceeds this (0.5) AND failed to contract
                        over the margin window — intervenes BEFORE the
                        health engine's residual_blowup bound (1.0)
    ``residual_low``    re-arm only when the margin is below this
                        (0.1) — the hysteresis gap
    ``margin_window``   steps of margin history per backoff check (8)
    ``margin_contract`` the margin must have contracted below this
                        fraction of its window-start value to count as
                        healthy warmup (0.9 — the stall-ratio idiom:
                        CHOCO's warmup legitimately runs margins near 1
                        while x̂ catches up, but a HEALTHY warmup
                        contracts; the γ ≫ ω run's margin plateaus)
    ``rearm_after``     consecutive healthy evaluations before any
                        re-arm (2)
    ``edge_slow_factor`` a measured edge slower than factor x the
                        median prefers the cost-reweighted mode (3.0)
    """
    every: int = 8
    cooldown: int = 16
    health_window: Optional[int] = None
    gamma_backoff: float = 0.5
    gamma_floor: float = 0.1
    gamma_rearm: float = 2.0
    residual_high: float = 0.5
    residual_low: float = 0.1
    margin_window: int = 8
    margin_contract: float = 0.9
    rearm_after: int = 2
    edge_slow_factor: float = 3.0

    @classmethod
    def from_env(cls) -> "ControlConfig":
        return cls(
            every=_env_int("EVERY", 8),
            cooldown=_env_int("COOLDOWN", 16),
            health_window=(_env_int("HEALTH_WINDOW", 0) or None),
            gamma_backoff=_env_float("GAMMA_BACKOFF", 0.5),
            gamma_floor=_env_float("GAMMA_FLOOR", 0.1),
            gamma_rearm=_env_float("GAMMA_REARM", 2.0),
            residual_high=_env_float("RESIDUAL_HIGH", 0.5),
            residual_low=_env_float("RESIDUAL_LOW", 0.1),
            margin_window=_env_int("MARGIN_WINDOW", 8),
            margin_contract=_env_float("MARGIN_CONTRACT", 0.9),
            rearm_after=_env_int("REARM_AFTER", 2),
            edge_slow_factor=_env_float("EDGE_SLOW_FACTOR", 3.0),
        )

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Decision:
    """One structured controller decision (the JSONL trail unit).

    ``knob``: ``"schedule"``, ``"gamma"``, or ``"cadence"``; ``action``:
    ``"switch"``, ``"backoff"``, ``"throttle"``, or ``"rearm"``.
    ``value``/``prev`` carry the new and previous knob values (mode NAME
    for schedule, γ-scale float for gamma, ``[rank, period]`` for
    cadence).  ``rule`` names the health verdict (or margin rule) that
    triggered it; ``mode``/``applied`` record whether this run actuated
    (``on``) or only would have (``shadow``)."""
    step: int
    knob: str
    action: str
    value: object
    prev: object
    rule: str
    reason: str
    mode: str = "shadow"
    applied: bool = False

    def asdict(self) -> dict:
        d = dataclasses.asdict(self)
        d["kind"] = "decision"
        return d

    def signature(self) -> Tuple:
        """The replay-parity identity: everything EXCEPT mode/applied
        (and wall time) — ``bfctl replay --expect`` compares these."""
        return (self.step, self.knob, self.action, self.value, self.rule)


def slow_edge(entries: Sequence[dict],
              factor: float) -> Optional[Tuple[int, int, float]]:
    """The slowest measured edge when it exceeds ``factor`` x the median
    one-way latency (largest-payload entries only — the bandwidth-regime
    numbers), else None.  Returns ``(src, dst, ratio)``."""
    best: Dict[Tuple[int, int], dict] = {}
    for e in entries or ():
        key = (int(e["src"]), int(e["dst"]))
        if key not in best or e["bytes"] > best[key]["bytes"]:
            best[key] = e
    lats = sorted(float(e["latency_us"]) for e in best.values())
    if len(lats) < 2:
        return None
    med = lats[len(lats) // 2]
    if med <= 0:
        return None
    (src, dst), worst = max(best.items(),
                            key=lambda kv: kv[1]["latency_us"])
    ratio = float(worst["latency_us"]) / med
    if ratio > factor:
        return src, dst, ratio
    return None


# health rules that map to the schedule knob (arXiv:2110.13363: one-peer
# dynamic graphs keep static-graph convergence at O(1) degree, so a
# stalled/unstable mix is worth a fresh per-step edge set)
_STALL_RULES = ("consensus_stall", "consensus_diverge")
# health rules that map to the gamma knob (the γ >> ω boundary)
_GAMMA_RULES = ("residual_blowup", "consensus_diverge")


class PolicyEngine:
    """Deterministic decision engine over one optimizer's knobs.

    ``modes``: schedule mode names available in the actuator's
    :class:`~.actuate.SwitchableSchedule` (empty = no schedule knob);
    ``initial_mode`` the mode the optimizer starts in; ``gamma`` whether
    the γ-scale knob exists (CHOCO compression).  The engine tracks the
    knob values it has DECIDED (in shadow mode the real system never
    moves, but the trail must read as if it had — that is what makes
    shadow-vs-on trails comparable and replayable)."""

    def __init__(self, cfg: Optional[ControlConfig] = None, *,
                 modes: Sequence[str] = (),
                 initial_mode: Optional[str] = None,
                 gamma: bool = False,
                 cadence=None):
        self.cfg = cfg or ControlConfig.from_env()
        self.modes = tuple(modes)
        if self.modes:
            self.sched_mode = initial_mode or self.modes[0]
            if self.sched_mode not in self.modes:
                raise ValueError(
                    f"initial mode {self.sched_mode!r} not in {self.modes}")
        else:
            self.sched_mode = None
        self.base_mode = self.sched_mode
        self.gamma = bool(gamma)
        self.gamma_scale = 1.0
        # cadence knob: a CadenceScheduler-like object (base_period /
        # max_staleness / periods) or its describe-dict from a replayed
        # trail head.  The engine MODELS the periods it has decided so
        # shadow trails read as if throttles had landed (replay parity).
        if cadence is not None:
            if isinstance(cadence, dict):
                self.cadence_base = int(cadence.get("base_period", 1))
                self.cadence_cap = int(cadence.get("max_staleness", 4))
                periods = cadence.get("periods", ())
            else:
                self.cadence_base = int(getattr(cadence, "base_period", 1))
                self.cadence_cap = int(cadence.max_staleness)
                periods = cadence.periods
            self.cadence_periods: Dict[int, int] = {
                i: int(p) for i, p in enumerate(periods)}
        else:
            self.cadence_base = 1
            self.cadence_cap = 0
            self.cadence_periods = {}
        self.cadence = cadence is not None
        self._last_step: Dict[str, int] = {}
        self._healthy_streak = 0
        self._deviated = False          # schedule moved off base_mode

    # -- sensing helpers ----------------------------------------------------

    @staticmethod
    def residual_margins(view, window: int) -> Tuple[float, float, int]:
        """``(now, then, samples)`` — max over ranks of
        ‖residual‖/‖param‖ at the newest step and at the start of the
        trailing ``window`` steps (plus how many window steps carried
        both fields).  ``now`` vs ``then`` is the γ-stability trend: a
        healthy CHOCO warmup runs margins near 1 but CONTRACTS them as
        x̂ catches up; the γ ≫ ω run's margin plateaus high
        (docs/compression.md "γ stability")."""
        now = then = 0.0
        samples = 0
        last = view.last_step()
        if last is None:
            return 0.0, 0.0, 0
        lo = last - window + 1
        for rank in view.ranks:
            res = dict(view.series_of(rank, "residual_norm"))
            pn = dict(view.series_of(rank, "param_norm"))
            common = sorted(s for s in set(res) & set(pn)
                            if s >= lo and pn[s] > 0)
            if not common:
                continue
            samples = max(samples, len(common))
            now = max(now, res[common[-1]] / pn[common[-1]])
            then = max(then, res[common[0]] / pn[common[0]])
        return now, then, samples

    def _cool(self, knob: str, step: int) -> bool:
        last = self._last_step.get(knob)
        return last is None or step - last >= self.cfg.cooldown

    def _preferred_mode(self, edges_entries) -> str:
        """The schedule mode a HEALTHY fleet should run: the
        fabric-synthesized schedule when one was compiled in (it was
        built FROM a usable measured matrix, so measured evidence is a
        precondition of the slot existing), else the cost-reweighted
        mode when the measured matrix shows a slow edge worth routing
        around (arXiv:2309.13541), else the base."""
        if "synthesized" in self.modes and edges_entries:
            return "synthesized"
        if "cost" in self.modes and edges_entries:
            worst = slow_edge(edges_entries, self.cfg.edge_slow_factor)
            if worst is not None:
                return "cost"
        return self.base_mode

    # -- the decision table -------------------------------------------------

    def evaluate(self, view, report, step: int,
                 edges: Optional[Sequence[dict]] = None) -> List[Decision]:
        """One policy pass at ``step``: the health report + fleet view
        (and optionally the measured edge entries) in, zero or more
        decisions out.  Mutates the engine's knob model — call in step
        order; decisions come back with ``mode="shadow"``/``applied=
        False`` and the caller (Controller / bfctl) stamps actuation."""
        cfg = self.cfg
        out: List[Decision] = []
        # series_gap alerts (truncated tails, mid-file garbage the
        # tolerant loader skipped) are I/O artifacts, not training
        # state — and a replay over the finished files cannot observe
        # them.  The engine's health notion excludes them so live and
        # replayed trails agree even on corrupted-but-tolerated series.
        relevant = [v for v in report.alerts if v.rule != "series_gap"]
        alerts = {v.rule for v in relevant}
        margin, margin_then, samples = (
            self.residual_margins(view, cfg.margin_window) if self.gamma
            else (0.0, 0.0, 0))

        if not relevant:
            self._healthy_streak += 1
        else:
            self._healthy_streak = 0

        # -- schedule knob ---------------------------------------------------
        if self.modes:
            stall = sorted(alerts & set(_STALL_RULES))
            if (stall and "dynamic" in self.modes
                    and self.sched_mode != "dynamic"
                    and self._cool("schedule", step)):
                out.append(self._decide(
                    step, "schedule", "switch", "dynamic", stall[0],
                    f"{stall[0]} active: switching to the one-peer "
                    f"dynamic exponential schedule (O(1) degree, same "
                    f"convergence class — arXiv:2110.13363)"))
                self._deviated = True
            elif (not stall and self._deviated
                    and self._healthy_streak >= cfg.rearm_after
                    and self._cool("schedule", step)):
                target = self._preferred_mode(edges)
                if target != self.sched_mode:
                    if target == "synthesized":
                        why = ("measured fabric available: re-arming onto "
                               "the synthesized bottleneck-optimal "
                               "schedule (arXiv:2309.13541)")
                    elif target == "cost":
                        why = ("measured slow edge persists: preferring "
                               "the cost-reweighted schedule "
                               "(arXiv:2309.13541)")
                    else:
                        why = ("consensus contracting again: restoring "
                               "the base schedule")
                    out.append(self._decide(
                        step, "schedule", "rearm", target, "rearm", why))
                    if target == self.base_mode:
                        self._deviated = False

        # -- gamma knob ------------------------------------------------------
        if self.gamma:
            trigger = sorted(alerts & set(_GAMMA_RULES))
            # high AND not contracting: healthy warmup margins are high
            # but fall; the unstable run's margin plateaus (hysteresis:
            # re-arm needs the DISTINCT residual_low floor below)
            high = (samples >= 2 and margin > cfg.residual_high
                    and margin > cfg.margin_contract * margin_then)
            if ((trigger or high) and self.gamma_scale > cfg.gamma_floor
                    and self._cool("gamma", step)):
                new = max(cfg.gamma_floor,
                          self.gamma_scale * cfg.gamma_backoff)
                rule = trigger[0] if trigger else "residual_margin"
                out.append(self._decide(
                    step, "gamma", "backoff", round(new, 6), rule,
                    f"{rule}: residual/param margin {margin:.3g} "
                    f"(window start {margin_then:.3g}) — backing CHOCO "
                    f"gamma off before the gamma >> omega divergence "
                    f"(docs/compression.md)"))
            elif (not relevant and not high and margin < cfg.residual_low
                    and self.gamma_scale < 1.0
                    and self._healthy_streak >= cfg.rearm_after
                    and self._cool("gamma", step)):
                new = min(1.0, self.gamma_scale * cfg.gamma_rearm)
                out.append(self._decide(
                    step, "gamma", "rearm", round(new, 6), "rearm",
                    f"consensus contracted (margin {margin:.3g} < "
                    f"{cfg.residual_low:g}): re-arming toward full-rate "
                    f"gossip"))

        # -- cadence knob ----------------------------------------------------
        # the PR 16 deferral: a straggler VERDICT lowers the flagged
        # rank's async cadence through the CadenceScheduler — bounded by
        # its max_staleness cap, restored when the verdict clears
        if self.cadence:
            stragglers = [v for v in relevant if v.rule == "straggler"
                          and getattr(v, "rank", None) is not None]
            if stragglers and self._cool("cadence", step):
                worst = max(stragglers, key=lambda v: float(v.value))
                rank = int(worst.rank)
                want = min(max(self.cadence_base,
                               math.ceil(float(worst.value))),
                           self.cadence_cap)
                if want != self.cadence_periods.get(rank,
                                                    self.cadence_base):
                    out.append(self._decide(
                        step, "cadence", "throttle", [rank, want],
                        "straggler",
                        f"rank {rank} runs {float(worst.value):.3g}x the "
                        f"fleet median step: lowering its async cadence "
                        f"to every {want} ticks (capped by "
                        f"max_staleness {self.cadence_cap})"))
            elif (not stragglers
                    and self._healthy_streak >= cfg.rearm_after
                    and self._cool("cadence", step)):
                throttled = sorted(
                    r for r, p in self.cadence_periods.items()
                    if p != self.cadence_base)
                if throttled:
                    rank = throttled[0]
                    out.append(self._decide(
                        step, "cadence", "rearm",
                        [rank, self.cadence_base], "rearm",
                        f"straggler verdict cleared: restoring rank "
                        f"{rank} to the base cadence "
                        f"({self.cadence_base})"))

        # an evaluation that INTERVENED is not a healthy steady state:
        # the re-arm streak starts counting after the last correction
        if any(d.action != "rearm" for d in out):
            self._healthy_streak = 0

        return out

    def _decide(self, step, knob, action, value, rule, reason) -> Decision:
        if knob == "schedule":
            prev = self.sched_mode
        elif knob == "cadence":
            rank = int(value[0])
            prev = [rank, self.cadence_periods.get(rank,
                                                   self.cadence_base)]
        else:
            prev = self.gamma_scale
        d = Decision(step=int(step), knob=knob, action=action, value=value,
                     prev=prev, rule=rule, reason=reason)
        if knob == "schedule":
            self.sched_mode = value
        elif knob == "cadence":
            self.cadence_periods[int(value[0])] = int(value[1])
        else:
            self.gamma_scale = float(value)
        self._last_step[knob] = int(step)
        return d

    def mode_index_view(self) -> int:
        """Index of the engine's MODELED schedule mode (what shadow mode
        mirrors to the ``bf_control_sched_mode`` gauge)."""
        if self.modes and self.sched_mode in self.modes:
            return self.modes.index(self.sched_mode)
        return 0

    def describe(self) -> dict:
        """The replayable engine identity (the ``control_config`` head
        record of a decision trail)."""
        out = {
            "modes": list(self.modes),
            "initial_mode": self.base_mode,
            "gamma": self.gamma,
            "cfg": self.cfg.asdict(),
        }
        if self.cadence:
            out["cadence"] = {
                "base_period": self.cadence_base,
                "max_staleness": self.cadence_cap,
                "periods": [self.cadence_periods[i]
                            for i in sorted(self.cadence_periods)],
            }
        return out


# ---------------------------------------------------------------------------
# Decision trail I/O (the JSONL the monitor tails and bfctl replays)
# ---------------------------------------------------------------------------

def write_config_record(path: str, describe: dict,
                        extra: Optional[dict] = None) -> None:
    """Open a decision trail with its ``control_config`` head record —
    everything ``bfctl replay`` needs to re-instantiate the engine
    (modes, initial mode, gamma knob, config, live platform)."""
    rec = {"kind": "control_config", "t_us": int(time.time() * 1e6)}
    rec.update(describe)
    if extra:
        rec.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def write_decision(path: str, decision: Decision,
                   header: Optional[dict] = None) -> dict:
    """Append one decision to the trail (size-bounded like the verdict
    trail: ``BLUEFOG_METRICS_MAX_MB`` rotation applies).

    ``header``: the engine's ``control_config`` describe-dict — written
    as the first line whenever the trail file does not exist yet, so a
    freshly opened AND a freshly ROTATED trail both carry the replayable
    head record (a rotation without it would orphan every later
    decision from its engine identity)."""
    from ..observability import export as _export
    max_bytes, keep = _export.resolve_rotation()
    if max_bytes:
        try:
            if os.path.getsize(path) >= max_bytes:
                _export.rotate_file(path, keep)
        except OSError:
            pass
    if header is not None and not os.path.exists(path):
        write_config_record(path, header)
    rec = decision.asdict()
    rec["t_us"] = int(time.time() * 1e6)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def read_decisions(path: str) -> Tuple[Optional[dict], List[dict]]:
    """Parse a decision trail tolerantly: ``(config_record, decisions)``
    — unknown lines are skipped, a missing file reads as empty (the
    monitor's discovery probe must never raise).  One shared reader
    serves every sidecar trail (``observability/export.py::read_trail``;
    the serving trail rides the same helper)."""
    from ..observability.export import read_trail
    return read_trail(path, "control_config", kinds=("decision",))
