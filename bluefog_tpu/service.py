"""Background communication service (reference parity: the core-runtime
background thread + handle manager, operations.cc:453-522 /
torch/handle_manager.{h,cc}, and the stall watchdog, operations.cc:388-433).

The native side (``csrc/service.cc``) owns the worker pool, the integer
handle table (pending/done/error + condition-variable waits), and the stall
watchdog.  Python submits closures; ctypes trampolines them onto the native
workers.  Two usage modes:

* ``submit(fn)`` — run ``fn`` on a worker, get a handle back immediately.
  Window ops use one shared lane so they retain the reference's
  single-comm-thread FIFO ordering (global_state.h:40-43) while staying off
  the caller's thread (true nonblocking enqueue, SURVEY.md §7 hard part 1b).
* ``alloc_handle()/mark_done()`` — use the native handle table for work
  completed elsewhere.

Falls back to synchronous inline execution when no native toolchain exists
(handles are then born done — semantics identical, latency hidden only by
JAX async dispatch).
"""

import atexit
import ctypes
import threading
from typing import Callable, Dict, List, Optional, Tuple

from . import native
from .observability import metrics as _metrics
from .utils import blog

__all__ = ["start", "stop", "running", "submit", "poll", "wait", "release",
           "pending", "WIN_LANE", "ServiceTaskError",
           "mark_rank_degraded", "degraded_ranks", "clear_degraded_ranks",
           "on_rank_degraded"]

# all window ops share one lane => FIFO like the reference's comm thread
WIN_LANE = 0

_lock = threading.Lock()
_lifecycle_lock = threading.Lock()
_tasks: Dict[int, Callable[[], None]] = {}
_results: Dict[int, object] = {}
_errors: Dict[int, str] = {}
_meta: Dict[int, Tuple[Optional[str], Optional[int]]] = {}  # handle -> (op, rank)
_next_tag = [1]
_trampoline_ref = []  # keep the CFUNCTYPE object alive for the process


class ServiceTaskError(RuntimeError):
    """A service-lane task failed.  Carries the submitting context — which
    op and which rank's work — so callers (and the chaos harness) can react
    per-rank instead of parsing strings out of the ``_errors`` dict.
    Subclasses RuntimeError: existing ``except RuntimeError`` paths and the
    reference's synchronize-raises semantics keep working."""

    def __init__(self, message: str, *, op_name: Optional[str] = None,
                 rank: Optional[int] = None, handle: Optional[int] = None):
        ctx_parts = []
        if op_name:
            ctx_parts.append(f"op={op_name}")
        if rank is not None:
            ctx_parts.append(f"rank={rank}")
        if handle is not None:
            ctx_parts.append(f"handle={handle}")
        suffix = f" [{', '.join(ctx_parts)}]" if ctx_parts else ""
        super().__init__(f"{message}{suffix}")
        self.message = message
        self.op_name = op_name
        self.rank = rank
        self.handle = handle


# -- degraded-rank registry (resilience integration) -------------------------
#
# The stall watchdog used to only LOG; now stalls and task errors that carry
# a rank mark that rank degraded here, and the resilience layer (membership /
# chaos harness) subscribes to feed it into liveness state.
_degraded: Dict[int, str] = {}
_degraded_callbacks: List[Callable[[int, str], None]] = []


def mark_rank_degraded(rank: int, reason: str) -> None:
    """Record a rank as degraded (stalled or erroring).  Idempotent per
    rank; fires registered callbacks and a timeline resilience event."""
    with _lock:
        first = rank not in _degraded
        _degraded[rank] = reason
        callbacks = list(_degraded_callbacks)
    if first:
        if _metrics.enabled():
            _metrics.counter("bf_service_degraded_total",
                             "ranks newly marked degraded").inc()
            _metrics.gauge("bf_service_degraded_ranks",
                           "ranks currently marked degraded").set(
                len(_degraded))
        blog.log(blog.WARN, f"rank {rank} marked degraded: {reason}")
        from . import timeline as _tl
        _tl.record_resilience_event("degraded", f"rank {rank}: {reason}")
        for cb in callbacks:
            try:
                cb(rank, reason)
            except Exception as e:  # a bad subscriber must not mask the op
                blog.log(blog.ERROR, f"degraded-rank callback failed: {e}")


def degraded_ranks() -> Dict[int, str]:
    """Ranks currently marked degraded, with the reason."""
    with _lock:
        return dict(_degraded)


def clear_degraded_ranks() -> None:
    with _lock:
        _degraded.clear()


def on_rank_degraded(callback: Callable[[int, str], None]) -> None:
    """Subscribe to degraded-rank transitions (e.g. the chaos harness
    folding watchdog verdicts into the liveness mask)."""
    with _lock:
        _degraded_callbacks.append(callback)


def _note_failure(handle: int) -> None:
    meta = _meta.get(handle)
    if meta and meta[1] is not None:
        mark_rank_degraded(meta[1], f"task error in {meta[0] or 'task'}")


def _trampoline(handle, tag):
    with _lock:
        fn = _tasks.pop(tag, None)
    if fn is None:
        return
    lib = native.load()
    try:
        result = fn()
        with _lock:
            _results[handle] = result
    except Exception as e:  # surfaced via the handle, like a Status callback
        with _lock:
            _errors[handle] = str(e)
        if lib is not None:
            lib.bft_handle_mark_error(handle, str(e).encode()[:512])
        blog.log(blog.ERROR, f"async task failed: {e}")


def _lib_or_none(num_threads: int = 0):
    lib = native.load()
    if lib is None:
        return None
    with _lifecycle_lock:
        if not _trampoline_ref:
            _trampoline_ref.append(native.SERVICE_CALLBACK(_trampoline))
        if not lib.bft_service_running():
            lib.bft_service_start(num_threads)
    return lib


def start(num_threads: int = 0) -> int:
    """Start the native worker pool (idempotent; returns the pool size).
    ``num_threads<=0`` reads ``BLUEFOG_NUM_SERVICE_THREADS`` (default 1)."""
    lib = _lib_or_none(num_threads)
    if lib is None:
        return 0
    # already-running pools keep their size (the native start reports it)
    return int(lib.bft_service_start(num_threads))


def stop() -> None:
    lib = native.load()
    if lib is not None and lib.bft_service_running():
        lib.bft_service_stop()
    with _lock:
        _tasks.clear()
        _results.clear()
        _errors.clear()
        _meta.clear()


def running() -> bool:
    lib = native.load()
    return bool(lib is not None and lib.bft_service_running())


def submit(fn: Callable[[], object], lane: int = -1, *,
           op_name: Optional[str] = None,
           rank: Optional[int] = None) -> int:
    """Run ``fn`` on a service worker; returns a handle immediately.

    The return value of ``fn`` is retrievable via :func:`wait`; exceptions
    mark the handle errored and re-raise at wait time (reference semantics:
    the status callback carries the error to ``synchronize``,
    torch/mpi_ops.cc:85-97).

    ``op_name``/``rank`` attach submitting context to the handle: a failing
    or stalling task then surfaces as a :class:`ServiceTaskError` carrying
    both, and the rank is marked degraded (:func:`degraded_ranks`).
    """
    if _metrics.enabled():
        _metrics.counter("bf_service_tasks_total",
                         "tasks submitted to the service").inc(
            op=op_name or "task")
    lib = _lib_or_none()
    if lib is None:
        # no native runtime: run inline; the handle is born completed
        with _lock:
            handle = -_next_tag[0] - 1
            _next_tag[0] += 1
            _meta[handle] = (op_name, rank)
        try:
            result = fn()
            with _lock:
                _results[handle] = result
        except Exception as e:
            with _lock:
                _errors[handle] = str(e)
            _note_failure(handle)
        return handle
    with _lock:
        tag = _next_tag[0]
        _next_tag[0] += 1
        _tasks[tag] = fn
    handle = int(lib.bft_service_submit(_trampoline_ref[0], tag, lane))
    if handle < 0:
        with _lock:
            _tasks.pop(tag, None)
        raise RuntimeError("service not running")
    with _lock:
        _meta[handle] = (op_name, rank)
    if _metrics.enabled():
        _metrics.gauge("bf_service_pending",
                       "tasks enqueued-but-unfinished on the service "
                       "(sampled at submit)").set(
            int(lib.bft_service_pending()))
    return handle


def _task_error(handle: int, message: str) -> ServiceTaskError:
    op_name, rank = _meta.get(handle, (None, None))
    return ServiceTaskError(message, op_name=op_name, rank=rank,
                            handle=handle)


def poll(handle: int, raise_error: bool = True) -> bool:
    """True when the task behind ``handle`` has completed.

    A completed-with-error handle raises its :class:`ServiceTaskError`
    immediately (structured raise path — errors no longer sit silently in
    the handle table until someone waits); pass ``raise_error=False`` for
    the bare done/pending answer."""
    lib = native.load()
    if handle < 0 or lib is None:  # inline fallback handle: born done
        done = True
    else:
        done = int(lib.bft_handle_poll(handle)) != 0
    if done and raise_error:
        with _lock:
            err = _errors.get(handle)
        if err is not None:
            exc = _task_error(handle, err)
            _note_failure(handle)
            raise exc
    return done


def wait(handle: int, timeout_ms: int = -1):
    """Block until the task completes; returns its result or raises its
    :class:`ServiceTaskError` (with op/rank context).  The handle is
    released.  A timeout marks the handle's rank degraded — the watchdog
    acts on the stall instead of only logging it."""
    if handle < 0 or native.load() is None:
        with _lock:
            err = _errors.pop(handle, None)
            if err is None:
                _meta.pop(handle, None)
                return _results.pop(handle, None)
        exc = _task_error(handle, err)
        _note_failure(handle)
        with _lock:
            _meta.pop(handle, None)
        raise exc
    lib = native.load()
    state = int(lib.bft_handle_wait(handle, timeout_ms))
    if state == 0:
        op_name, rank = _meta.get(handle, (None, None))
        if _metrics.enabled():
            # stall-watchdog fire: a wait deadline elapsed with the task
            # still pending — the queue-health alarm series
            _metrics.counter("bf_service_stalls_total",
                             "wait timeouts on pending handles").inc(
                op=op_name or "task")
        if rank is not None:
            mark_rank_degraded(
                rank, f"{op_name or 'task'} still pending after "
                      f"{timeout_ms}ms")
        raise TimeoutError(f"handle {handle} still pending after "
                           f"{timeout_ms}ms")
    if state == -2:
        raise RuntimeError(
            f"handle {handle} is unknown (already waited/released, or the "
            f"service was stopped before the task ran)")
    try:
        if state == 2:
            with _lock:
                err = _errors.pop(handle, None)
            if err is None:
                cbuf = ctypes.create_string_buffer(512)
                lib.bft_handle_error_msg(handle, cbuf, 512)
                err = cbuf.value.decode(errors="replace")
            exc = _task_error(handle, err)
            _note_failure(handle)
            raise exc
        with _lock:
            return _results.pop(handle, None)
    finally:
        lib.bft_handle_release(handle)
        with _lock:
            _errors.pop(handle, None)
            _results.pop(handle, None)
            _meta.pop(handle, None)


def release(handle: int) -> None:
    lib = native.load()
    if lib is not None and handle >= 0:
        lib.bft_handle_release(handle)
    with _lock:
        _results.pop(handle, None)
        _errors.pop(handle, None)
        _meta.pop(handle, None)


def pending() -> int:
    lib = native.load()
    if lib is None:
        return 0
    return int(lib.bft_service_pending())


# join native workers before interpreter teardown (static-destructor order
# in the shared library is otherwise undefined across platforms)
atexit.register(stop)
