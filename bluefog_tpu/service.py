"""Background communication service (reference parity: the core-runtime
background thread + handle manager, operations.cc:453-522 /
torch/handle_manager.{h,cc}, and the stall watchdog, operations.cc:388-433).

The native side (``csrc/service.cc``) owns the worker pool, the integer
handle table (pending/done/error + condition-variable waits), and the stall
watchdog.  Python submits closures; ctypes trampolines them onto the native
workers.  Two usage modes:

* ``submit(fn)`` — run ``fn`` on a worker, get a handle back immediately.
  Window ops use one shared lane so they retain the reference's
  single-comm-thread FIFO ordering (global_state.h:40-43) while staying off
  the caller's thread (true nonblocking enqueue, SURVEY.md §7 hard part 1b).
* ``alloc_handle()/mark_done()`` — use the native handle table for work
  completed elsewhere.

Falls back to synchronous inline execution when no native toolchain exists
(handles are then born done — semantics identical, latency hidden only by
JAX async dispatch).
"""

import atexit
import ctypes
import threading
from typing import Callable, Dict

from . import native
from .utils import blog

__all__ = ["start", "stop", "running", "submit", "poll", "wait", "release",
           "pending", "WIN_LANE"]

# all window ops share one lane => FIFO like the reference's comm thread
WIN_LANE = 0

_lock = threading.Lock()
_lifecycle_lock = threading.Lock()
_tasks: Dict[int, Callable[[], None]] = {}
_results: Dict[int, object] = {}
_errors: Dict[int, str] = {}
_next_tag = [1]
_trampoline_ref = []  # keep the CFUNCTYPE object alive for the process


def _trampoline(handle, tag):
    with _lock:
        fn = _tasks.pop(tag, None)
    if fn is None:
        return
    lib = native.load()
    try:
        result = fn()
        with _lock:
            _results[handle] = result
    except Exception as e:  # surfaced via the handle, like a Status callback
        with _lock:
            _errors[handle] = str(e)
        if lib is not None:
            lib.bft_handle_mark_error(handle, str(e).encode()[:512])
        blog.log(blog.ERROR, f"async task failed: {e}")


def _lib_or_none(num_threads: int = 0):
    lib = native.load()
    if lib is None:
        return None
    with _lifecycle_lock:
        if not _trampoline_ref:
            _trampoline_ref.append(native.SERVICE_CALLBACK(_trampoline))
        if not lib.bft_service_running():
            lib.bft_service_start(num_threads)
    return lib


def start(num_threads: int = 0) -> int:
    """Start the native worker pool (idempotent; returns the pool size).
    ``num_threads<=0`` reads ``BLUEFOG_NUM_SERVICE_THREADS`` (default 1)."""
    lib = _lib_or_none(num_threads)
    if lib is None:
        return 0
    # already-running pools keep their size (the native start reports it)
    return int(lib.bft_service_start(num_threads))


def stop() -> None:
    lib = native.load()
    if lib is not None and lib.bft_service_running():
        lib.bft_service_stop()
    with _lock:
        _tasks.clear()
        _results.clear()
        _errors.clear()


def running() -> bool:
    lib = native.load()
    return bool(lib is not None and lib.bft_service_running())


def submit(fn: Callable[[], object], lane: int = -1) -> int:
    """Run ``fn`` on a service worker; returns a handle immediately.

    The return value of ``fn`` is retrievable via :func:`wait`; exceptions
    mark the handle errored and re-raise at wait time (reference semantics:
    the status callback carries the error to ``synchronize``,
    torch/mpi_ops.cc:85-97).
    """
    lib = _lib_or_none()
    if lib is None:
        # no native runtime: run inline; the handle is born completed
        with _lock:
            handle = -_next_tag[0] - 1
            _next_tag[0] += 1
        try:
            result = fn()
            with _lock:
                _results[handle] = result
        except Exception as e:
            with _lock:
                _errors[handle] = str(e)
        return handle
    with _lock:
        tag = _next_tag[0]
        _next_tag[0] += 1
        _tasks[tag] = fn
    handle = int(lib.bft_service_submit(_trampoline_ref[0], tag, lane))
    if handle < 0:
        with _lock:
            _tasks.pop(tag, None)
        raise RuntimeError("service not running")
    return handle


def poll(handle: int) -> bool:
    if handle < 0:  # inline fallback handle
        return True
    lib = native.load()
    if lib is None:
        return True
    return int(lib.bft_handle_poll(handle)) != 0


def wait(handle: int, timeout_ms: int = -1):
    """Block until the task completes; returns its result or raises its
    exception.  The handle is released."""
    if handle < 0 or native.load() is None:
        with _lock:
            err = _errors.pop(handle, None)
            if err is None:
                return _results.pop(handle, None)
        raise RuntimeError(err)
    lib = native.load()
    state = int(lib.bft_handle_wait(handle, timeout_ms))
    if state == 0:
        raise TimeoutError(f"handle {handle} still pending after "
                           f"{timeout_ms}ms")
    if state == -2:
        raise RuntimeError(
            f"handle {handle} is unknown (already waited/released, or the "
            f"service was stopped before the task ran)")
    try:
        if state == 2:
            with _lock:
                err = _errors.pop(handle, None)
            if err is None:
                cbuf = ctypes.create_string_buffer(512)
                lib.bft_handle_error_msg(handle, cbuf, 512)
                err = cbuf.value.decode(errors="replace")
            raise RuntimeError(err)
        with _lock:
            return _results.pop(handle, None)
    finally:
        lib.bft_handle_release(handle)
        with _lock:
            _errors.pop(handle, None)
            _results.pop(handle, None)


def release(handle: int) -> None:
    lib = native.load()
    if lib is not None and handle >= 0:
        lib.bft_handle_release(handle)
    with _lock:
        _results.pop(handle, None)
        _errors.pop(handle, None)


def pending() -> int:
    lib = native.load()
    if lib is None:
        return 0
    return int(lib.bft_service_pending())


# join native workers before interpreter teardown (static-destructor order
# in the shared library is otherwise undefined across platforms)
atexit.register(stop)
