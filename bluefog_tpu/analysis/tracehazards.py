"""StableHLO trace-hazard pass: program-level invariants the AST cannot
see, checked on the LOWERED step (``bflint --trace``).

The AST rules catch drift in source conventions; three hazard classes
only exist in the lowered program:

``trace-donation-dropped``
    A step built with ``donate=True`` whose inputs lost their
    input→output aliasing (``tf.aliasing_output`` arg attributes in the
    StableHLO signature).  XLA then keeps both the argument and the
    result buffers live — a silent 2× HBM cost on the largest arrays in
    the job.  jax only warns on stderr, once, where nobody looks.
``trace-wire-upcast``
    A ``collective_permute`` whose operand is produced by a WIDENING
    ``stablehlo.convert`` (e.g. i8 → f32 dequantize *before* the send):
    the wire then moves the wide dtype and the compression win silently
    evaporates.  The legal shape is send-then-dequantize — the convert
    consumes the permute's result, never feeds it.
``trace-collective-budget``
    The step's ``collective_permute`` count must equal the fusion plan's
    budget (``buckets × offsets × wire arrays per bucket``) — an extra
    permute means a leaf escaped the flat-buffer path (per-leaf traffic
    snuck back in); a missing one means an exchange silently dropped.
    Under ``BLUEFOG_GOSSIP_KERNEL`` the hot path has NO standalone
    permutes at all — the RDMA lives inside the fused kernel — so the
    budget flips: ``pallas_call`` EXECUTIONS (``tpu_custom_call``
    custom-calls, counted through the call graph because XLA dedupes
    identical kernel wrappers into one shared function) must equal the
    bucket count and the permute count must be ZERO.

All three run over the text :func:`~..utils.trace_metrics.lower_text`
produces, so the pass is CPU-only and backend-free like the rest of the
trace-metrics evidence.  :func:`run_canonical_trace_checks` applies them
to the canonical ``bench.py --trace-only`` configs (the fused f32 and
fused+int8 train steps, built ``donate=True``), plus — lowered for the
TPU platform via ``jax.export`` (Mosaic serialization needs no device)
— the fused-int8 step with the gossip kernel ON, which is what
``make lint`` and ``tests/test_lint_clean.py`` gate on.
"""

import re
from typing import Dict, List, Optional, Tuple

from .findings import Finding

__all__ = ["TRACE_RULES", "check_donation", "find_wire_upcasts",
           "count_pallas_calls_in_text", "check_collective_budget",
           "analyze_trace", "run_canonical_trace_checks"]

TRACE_RULES = ("trace-donation-dropped", "trace-wire-upcast",
               "trace-collective-budget")

# donation has three dialect spellings: `tf.aliasing_output` when jax
# resolves the alias at trace time (unsharded args), `jax.buffer_donor`
# when the decision defers to compile (sharded/global-view args — the
# canonical train steps), and the compiled HLO's `input_output_alias`
# entries.  A DROPPED donation erases the attribute entirely (jax only
# warns on stderr), which is what the counter-vs-expected check catches.
_ALIASED = re.compile(r"tf\.aliasing_output")
_DONOR = re.compile(r"jax\.buffer_donor")
_HLO_ALIAS = re.compile(r"\b(?:may|must)-alias\b")
# `%0 = stablehlo.convert %arg1 : (tensor<1x16xi8>) -> tensor<1x16xf32>`
_CONVERT = re.compile(
    r"%([A-Za-z0-9_.#]+)\s*=\s*stablehlo\.convert\s+%[A-Za-z0-9_.#]+\s*:"
    r"\s*\(tensor<([^>]*)>\)\s*->\s*tensor<([^>]*)>")
# `%1 = "stablehlo.collective_permute"(%0) <{...}>` (generic form) or
# `stablehlo.collective_permute %0, ...` (pretty form)
_PERMUTE_OPERAND = re.compile(
    r"\"?stablehlo\.collective_permute\"?[ (]+%([A-Za-z0-9_.#]+)")


def _tensor_dtype_bytes(spec: str) -> int:
    """Per-element width of a ``AxBxDT`` tensor spec (0 when unknown)."""
    from ..utils.trace_metrics import _dtype_nbytes
    return _dtype_nbytes(spec.strip().split("x")[-1].strip()) or 0


def donation_marks(text: str) -> int:
    """Count of donation/alias marks in a lowered (StableHLO) or
    compiled (HLO) program text, whichever dialect ``text`` is in."""
    stablehlo = len(_ALIASED.findall(text)) + len(_DONOR.findall(text))
    hlo = len(_HLO_ALIAS.findall(text))
    return max(stablehlo, hlo)


def check_donation(text: str, label: str,
                   expected_aliased: int) -> List[Finding]:
    """``expected_aliased``: the donated input leaves the builder knows
    it passed (the text alone cannot show a donation XLA dropped — the
    attribute is simply absent, which is exactly the silence this rule
    exists to break)."""
    aliased = donation_marks(text)
    if aliased >= expected_aliased:
        return []
    return [Finding(
        "trace-donation-dropped", "error", f"<trace:{label}>", 0,
        f"step was built donate=True over {expected_aliased} input "
        f"leaves but only {aliased} carry a donation/alias mark "
        f"(tf.aliasing_output / jax.buffer_donor / input_output_alias) "
        f"in the lowered program — XLA keeps both buffers live for "
        f"every dropped donation (silent 2x HBM on the biggest arrays)")]


# pallas kernels lower to `stablehlo.custom_call @tpu_custom_call` with
# the Mosaic module serialized in backend_config; interpret-mode
# lowerings instead inline the body into private functions jax names
# after the kernel (`*_gossip_kernel*` / `*kernel*`) — converts in THERE
# are the kernel's in-register decode, not a wire upcast
_PALLAS_CALL = re.compile(r"stablehlo\.custom_call\s+@tpu_custom_call")
# jax.export prints `func.func public @main`; jit lowerings print bare
# `func.func @main` and `func.func private @helper` — all three shapes
# must parse or the call-graph walk loses its roots
_FUNC_DEF = re.compile(
    r"func\.func\s+(?:(?P<vis>private|public)\s+)?@(?P<name>[\w$.\-]+)")
_CALLSITE = re.compile(r"\bcall\s+@([\w$.\-]+)")
_KERNEL_FN = re.compile(r"kernel")


def count_pallas_calls_in_text(text: str) -> int:
    """Number of pallas_call EXECUTIONS the program performs: direct
    ``tpu_custom_call`` occurrences plus call-graph multiplicity — XLA
    dedupes identical kernel wrapper functions (two same-shape buckets
    share one ``func.func`` containing the custom-call, invoked twice),
    so a flat text count under-reports the per-step kernel launches the
    budget rule is about."""
    funcs: Dict[str, Dict] = {}
    current = None
    roots: List[str] = []
    for line in text.splitlines():
        m = _FUNC_DEF.search(line)
        if m:
            current = m.group("name")
            funcs[current] = {"direct": 0, "calls": []}
            if m.group("vis") != "private":
                roots.append(current)
            continue
        if current is None:
            continue
        if _PALLAS_CALL.search(line):
            funcs[current]["direct"] += 1
        for c in _CALLSITE.findall(line):
            funcs[current]["calls"].append(c)

    memo: Dict[str, int] = {}

    def execs(name: str, stack=()) -> int:
        if name in memo:
            return memo[name]
        if name not in funcs or name in stack:
            return 0
        f = funcs[name]
        total = f["direct"] + sum(execs(c, stack + (name,))
                                  for c in f["calls"])
        memo[name] = total
        return total

    if not roots:
        roots = [n for n in funcs if n == "main"] or list(funcs)[:1]
    return sum(execs(r) for r in roots)


def _kernel_body_functions(text: str) -> set:
    """Names of functions that ARE a pallas kernel body: interpret-mode
    lowerings inline the kernel into private functions named after it
    (the real Mosaic lowering serializes the body invisibly instead)."""
    out = set()
    for line in text.splitlines():
        m = _FUNC_DEF.search(line)
        if m and _KERNEL_FN.search(m.group("name")):
            out.add(m.group("name"))
    return out


def find_wire_upcasts(text: str, label: str,
                      kernel: bool = False) -> List[Finding]:
    """``kernel=True`` (a trace KNOWN to carry a gossip-kernel lowering,
    e.g. the ``fused_int8_kernel`` canonical config): converts inside a
    kernel-body function (interpret-mode lowerings inline the body into
    functions named after the kernel) are the kernel's in-register
    decode and are skipped.  The exemption is scoped to kernel-mode
    traces ONLY — on a plain trace a user function that merely has
    "kernel" in its name keeps the full check (the name is not
    evidence)."""
    findings: List[Finding] = []
    widening: Dict[str, Tuple[str, str]] = {}
    kernel_fns = _kernel_body_functions(text) if kernel else set()
    in_kernel_body = False
    for lineno, line in enumerate(text.splitlines(), 1):
        m_fn = _FUNC_DEF.search(line)
        if m_fn:
            # SSA names are function-scoped; never match a convert from
            # another function's region
            widening.clear()
            in_kernel_body = m_fn.group("name") in kernel_fns
            continue
        if "func.func" in line:
            widening.clear()
            in_kernel_body = False
            continue
        if in_kernel_body:
            continue
        m = _CONVERT.search(line)
        if m:
            name, src_spec, dst_spec = m.groups()
            if (_tensor_dtype_bytes(dst_spec)
                    > _tensor_dtype_bytes(src_spec) > 0):
                widening[name] = (src_spec.split("x")[-1],
                                  dst_spec.split("x")[-1])
            continue
        if "collective_permute" in line:
            p = _PERMUTE_OPERAND.search(line)
            if p and p.group(1) in widening:
                src_dt, dst_dt = widening[p.group(1)]
                findings.append(Finding(
                    "trace-wire-upcast", "error", f"<trace:{label}>",
                    lineno,
                    f"collective_permute operand %{p.group(1)} is "
                    f"produced by a widening convert {src_dt} -> "
                    f"{dst_dt}: the wire moves the wide dtype "
                    f"(dequantize-before-send) — move the convert to "
                    f"the receive side"))
    return findings


def check_collective_budget(text: str, label: str, expected: int,
                            kernel: bool = False,
                            expected_pallas_calls: Optional[int] = None
                            ) -> List[Finding]:
    """``kernel=False``: the classic budget — permute count must equal
    ``expected`` (buckets × offsets × wire arrays).  ``kernel=True``
    (the ``BLUEFOG_GOSSIP_KERNEL`` hot path): ``expected`` standalone
    permutes are still allowed for NON-gossip traffic (0 on the
    canonical configs), and ``expected_pallas_calls`` (the bucket
    count) pallas_call executions must be present — a missing kernel
    means a bucket silently fell back to the chain."""
    from ..utils.trace_metrics import count_collectives_in_text
    got = count_collectives_in_text(text)["ppermute"]
    findings: List[Finding] = []
    if got != expected:
        if kernel:
            direction = ("a bucket fell back to the ppermute chain — "
                         "the fused kernel is not carrying the wire"
                         if got > expected
                         else "an exchange silently dropped out of the "
                              "step")
            budget_desc = "kernel-mode permute budget"
        else:
            direction = ("a pytree leaf escaped the fusion plan "
                         "(per-leaf traffic is back)" if got > expected
                         else "an exchange silently dropped out of the "
                              "step")
            budget_desc = "fusion plan budgets"
        findings.append(Finding(
            "trace-collective-budget", "error", f"<trace:{label}>", 0,
            f"lowered step has {got} collective_permute(s), "
            f"{budget_desc} {expected} — {direction}"))
    if kernel and expected_pallas_calls is not None:
        calls = count_pallas_calls_in_text(text)
        if calls != expected_pallas_calls:
            direction = ("an extra kernel launch appeared (a bucket "
                         "split the hot path in two)"
                         if calls > expected_pallas_calls
                         else "a bucket's exchange lost its fused "
                              "kernel (chain fallback or dropped "
                              "exchange)")
            findings.append(Finding(
                "trace-collective-budget", "error", f"<trace:{label}>",
                0,
                f"lowered kernel-mode step executes {calls} "
                f"pallas_call(s), budget is {expected_pallas_calls} "
                f"(one per fusion bucket) — {direction}"))
    return findings


def analyze_trace(text: str, label: str, *, expected_aliased: int = 0,
                  expected_ppermutes: int = None, kernel: bool = False,
                  expected_pallas_calls: int = None) -> List[Finding]:
    """All three checks over one lowered program (test entry point for
    constructed violation programs).  ``kernel``/``expected_pallas_
    calls``: the gossip-kernel budget flavor (see
    :func:`check_collective_budget`)."""
    findings = []
    if expected_aliased:
        findings += check_donation(text, label, expected_aliased)
    findings += find_wire_upcasts(text, label, kernel=kernel)
    if expected_ppermutes is not None or expected_pallas_calls is not None:
        findings += check_collective_budget(
            text, label, expected_ppermutes or 0, kernel=kernel,
            expected_pallas_calls=expected_pallas_calls)
    return findings


# wire arrays each codec moves per fusion bucket per offset: the payload
# alone uncompressed; payload + per-bucket scales under int8 (the
# canonical compressed config — matches bench.py --trace-only)
_CANONICAL_CONFIGS = (
    ("fused", None, 1),
    ("fused_int8", "int8", 2),
)


def export_kernel_step_text(step, *args) -> str:
    """Lower a gossip-kernel train step for the TPU platform from any
    host via ``jax.export`` — Mosaic kernel serialization happens at
    lowering time and needs no TPU device, so the one-pallas_call-per-
    bucket invariant is checkable on the CPU CI mesh (the CPU lowering
    path itself refuses non-interpret pallas calls)."""
    from jax import export as _export
    return _export.export(step, platforms=["tpu"])(*args).mlir_module()


def run_canonical_trace_checks(depth: int = 8
                               ) -> Tuple[List[Finding], Dict]:
    """Lower the canonical bench-trace train steps (fused f32, fused
    int8 — both ``donate=True``) and run every trace check.  Returns
    ``(findings, report)``; report carries the measured counts for
    ``--json`` output.  Needs an initialized context (or initializes the
    default one) on a mesh of >= 2 devices."""
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    from .. import context as _ctx
    from .. import training as T
    from ..models.mlp import MLP
    from ..ops import fusion as fusion_mod
    from ..utils import trace_metrics as TM

    if _ctx.is_initialized():
        cx = _ctx.ctx()
    elif len(jax.devices()) < 2:
        # guard BEFORE bf.init(): a 1-device backend cannot host the
        # exchange topology at all — report the skip instead of crashing
        return [], {"mesh": len(jax.devices()),
                    "skipped": "backend has a single device — no "
                               "exchange to lower"}
    else:
        cx = bf.init()
    n = cx.size
    report: Dict[str, Dict] = {"mesh": n}
    if n < 2:
        report["skipped"] = "mesh has a single device — no exchange"
        return [], report
    model = MLP(features=(32,) * depth, num_outputs=10)
    base = optax.sgd(0.01, momentum=0.9)
    offsets = len(cx.compiled_topology.offsets)
    x = jnp.zeros((n, 4, 8, 8, 1), jnp.float32)
    y = jnp.zeros((n, 4), jnp.int32)
    findings: List[Finding] = []
    for label, spec, arrays in _CANONICAL_CONFIGS:
        variables, opt_state = T.create_train_state(
            model, base, jax.random.key(0), jnp.zeros((1, 8, 8, 1)),
            fuse=True, overlap=False, compression=spec)
        # gossip_kernel pinned OFF: the chain configs' budgets are the
        # ppermute counts, and an ambient BLUEFOG_GOSSIP_KERNEL (docs
        # tell operators to export it for `make bench-hw`) would flip
        # them to a Mosaic lowering the CPU path refuses
        step = T.make_train_step(
            model, base, communication="neighbor_allreduce", fuse=True,
            overlap=False, telemetry=False, compression=spec,
            gossip_kernel=False, donate=True)
        text, trace_s = TM.lower_text(
            step, variables, opt_state, (x, y), jnp.int32(0))
        per_rank = jax.tree.map(lambda a: a[0], variables["params"])
        plan = fusion_mod.plan_for(per_rank)
        expected_pp = plan.n_buckets * offsets * arrays
        donated = (len(jax.tree.leaves(variables))
                   + len(jax.tree.leaves(opt_state)))
        fs = analyze_trace(text, label, expected_aliased=donated,
                           expected_ppermutes=expected_pp)
        findings += fs
        report[label] = {
            "ppermute": TM.count_collectives_in_text(text)["ppermute"],
            "expected_ppermute": expected_pp,
            "donated_leaves": donated,
            "aliased_outputs": donation_marks(text),
            "buckets": plan.n_buckets,
            "offsets": offsets,
            "trace_s": round(trace_s, 3),
            "findings": len(fs),
        }

    # the gossip-kernel configs: lowered for TPU via jax.export (Mosaic
    # needs no device at lowering time) — each per-bucket hot path must
    # be exactly one pallas_call with ZERO standalone collective_permutes
    # and zero widening wire converts.  Three flavors: direct int8 (PR
    # 15), CHOCO-under-kernel (the estimates fold in-register), and the
    # hybrid (dp, fsdp) train step reaching the SAME bucket-kernel entry
    # with mesh-coordinate RDMA addressing.
    def kernel_leg(label, lower_fn):
        try:
            text, buckets = lower_fn()
        except Exception as e:      # noqa: BLE001 — an un-lowerable
            # kernel config must FAIL the lint pass loudly, not print
            # clean
            findings.append(Finding(
                "trace-pass-skipped", "error", f"<trace:{label}>", 0,
                f"gossip-kernel canonical config failed to lower via "
                f"jax.export(platforms=['tpu']): {type(e).__name__}: {e}"))
            report[label] = {"skipped": f"{type(e).__name__}: {e}"}
            return
        fs = analyze_trace(text, label, expected_ppermutes=0, kernel=True,
                           expected_pallas_calls=buckets)
        findings.extend(fs)
        report[label] = {
            "ppermute": TM.count_collectives_in_text(text)["ppermute"],
            "pallas_calls": count_pallas_calls_in_text(text),
            "expected_pallas_calls": buckets,
            "buckets": buckets,
            "offsets": offsets,
            "findings": len(fs),
        }

    def lower_replicated(spec):
        variables, opt_state = T.create_train_state(
            model, base, jax.random.key(0), jnp.zeros((1, 8, 8, 1)),
            fuse=True, overlap=False, compression=spec)
        step = T.make_train_step(
            model, base, communication="neighbor_allreduce", fuse=True,
            overlap=False, telemetry=False, compression=spec,
            gossip_kernel="pallas", donate=True)
        text = export_kernel_step_text(
            step, variables, opt_state,
            (jnp.zeros((n, 4, 8, 8, 1), jnp.float32),
             jnp.zeros((n, 4), jnp.int32)), jnp.int32(0))
        per_rank = jax.tree.map(lambda a: a[0], variables["params"])
        return text, fusion_mod.plan_for(per_rank).n_buckets

    kernel_leg("fused_int8_kernel", lambda: lower_replicated("int8"))
    kernel_leg("fused_choco_kernel",
               lambda: lower_replicated("choco:int8:gamma=0.5"))

    def lower_hybrid():
        from ..parallel import topology as topo_mod
        from ..parallel.fsdp import (dfsdp_mesh, fsdp_specs,
                                     make_decentralized_fsdp_lm_train_step)
        from ..parallel.schedule import compile_topology
        if n < 4 or n % 2:
            raise RuntimeError(
                f"hybrid (dp, fsdp) canonical config needs an even mesh "
                f"of >= 4 devices, have {n}")
        dp, fs_ = n // 2, 2
        mesh = dfsdp_mesh(dp, fs_)
        step, place = make_decentralized_fsdp_lm_train_step(
            model, base, mesh,
            topo=compile_topology(topo_mod.ExponentialGraph(dp)),
            donate=True, fuse=True, compression="choco:int8:gamma=0.5",
            gossip_kernel="pallas")
        single = model.init(jax.random.key(0),
                            jnp.zeros((1, 8, 8, 1)))["params"]
        gp, go = place(single)
        text = export_kernel_step_text(
            step, gp, go, jnp.zeros((dp, 4, 8, 8, 1), jnp.float32),
            jnp.zeros((dp, 4), jnp.int32), jnp.int32(0))
        plan = fusion_mod.shard_plan_for(
            single, fsdp_specs(single, mesh, axis="fsdp"), {"fsdp": fs_})
        return text, plan.n_buckets

    kernel_leg("hybrid_choco_kernel", lower_hybrid)
    return findings, report
