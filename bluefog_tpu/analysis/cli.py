"""``bflint`` — the project-invariant linter (docs/static_analysis.md).

Modes::

    bflint                  # AST contract rules over the checkout
    bflint --trace          # + StableHLO trace-hazard pass (canonical
                            #   bench-trace configs on the virtual mesh)
    bflint --json           # machine output (one JSON object)
    bflint --rules a,b      # run a rule subset
    bflint --baseline PATH  # non-default suppression file

Exit status: 0 iff zero unsuppressed findings AND zero stale baseline
entries — the ``make lint`` pre-PR gate.  Human output is one line per
finding plus a bfmonitor-style summary; ``--json`` carries the same
fields (rule, severity, file, line, message) so CI logs and humans read
the same report.
"""

import argparse
import os
import sys
from typing import List, Optional

from . import astrules, baseline as baseline_mod
from .findings import Finding, format_json, format_text, summary_line

__all__ = ["main"]


def _force_virtual_mesh() -> None:
    """The trace pass lowers the canonical train steps, which needs a
    multi-device mesh; mirror ``bench.py --trace-only``'s CPU forcing —
    this must happen before the first backend use."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bflint",
        description="project-invariant static analysis: AST contract "
                    "rules + StableHLO trace-hazard pass "
                    "(docs/static_analysis.md)")
    ap.add_argument("--trace", action="store_true",
                    help="also run the trace-hazard pass over the "
                         "canonical bench-trace step configs")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine output: one JSON object with findings "
                         "(rule, severity, file, line, message)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated AST rule subset "
                         f"(known: {', '.join(astrules.ALL_RULES)})")
    ap.add_argument("--baseline", default=baseline_mod.DEFAULT_PATH,
                    help="suppression file (default: the checked-in "
                         "analysis/baseline.toml)")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this checkout)")
    args = ap.parse_args(argv)

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    trace_report = None
    try:
        findings, n_files = astrules.run_ast_rules(args.root, rules)
    except ValueError as e:
        print(f"bflint: {e}", file=sys.stderr)
        return 2
    rules_run = list(rules or astrules.ALL_RULES)
    if args.trace:
        _force_virtual_mesh()
        from . import tracehazards
        trace_findings, trace_report = \
            tracehazards.run_canonical_trace_checks()
        if "skipped" in trace_report:
            # a gate that silently skips its trace half still exits 0 —
            # the exact silence this tool exists to break; fail loudly
            trace_findings = list(trace_findings) + [Finding(
                "trace-pass-skipped", "error", "<trace>", 0,
                f"trace-hazard pass did not run: "
                f"{trace_report['skipped']} — check XLA_FLAGS "
                f"--xla_force_host_platform_device_count (an existing "
                f"=1 flag wins over bflint's default of 8)")]
        findings = findings + trace_findings
        rules_run += list(tracehazards.TRACE_RULES)

    try:
        entries = baseline_mod.load_baseline(args.baseline)
    except baseline_mod.BaselineError as e:
        print(f"bflint: {e}", file=sys.stderr)
        return 2
    kept, suppressed, stale = baseline_mod.apply(findings, entries)
    for e in stale:
        kept.append(Finding(
            "stale-suppression", "warn", os.path.relpath(args.baseline),
            e["_line"],
            f"baseline entry (rule={e['rule']!r}, path={e['path']!r}) "
            f"matched no finding — delete the dead suppression"))

    if args.as_json:
        import json
        payload = json.loads(format_json(kept, suppressed, rules_run))
        if trace_report is not None:
            payload["trace"] = trace_report
        payload["files"] = n_files
        print(json.dumps(payload))
    else:
        if kept:
            print(format_text(kept))
        print(summary_line(kept, n_files, len(rules_run), suppressed))
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
