"""Finding model shared by the AST contract rules and the trace-hazard
pass: one structured record per violation, with a stable rule id the
baseline file and the ``--json`` output key off.

Severities: ``error`` findings gate ``make lint`` / CI; ``warn`` findings
gate too (the pre-PR bar is zero findings of any severity on a clean
tree) but signal doc-side staleness rather than a live code hazard.
"""

import json
from typing import Iterable, List, NamedTuple, Optional

__all__ = ["Finding", "format_text", "format_json", "summary_line",
           "SEVERITIES"]

SEVERITIES = ("error", "warn")


class Finding(NamedTuple):
    """One rule violation.

    ``path`` is repo-relative for file findings; trace-hazard findings
    use a ``<trace:config>`` pseudo-path (there is no source line for a
    property of a lowered program) with ``line`` 0.
    """
    rule: str
    severity: str
    path: str
    line: int
    message: str

    def asdict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "file": self.path, "line": self.line,
                "message": self.message}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.severity}] "
                f"{self.rule}: {self.message}")


def format_text(findings: Iterable[Finding]) -> str:
    return "\n".join(f.render() for f in findings)


def format_json(findings: Iterable[Finding], suppressed: int = 0,
                rules_run: Optional[List[str]] = None) -> str:
    """Machine output (one JSON object): the same fields a human reads,
    so CI logs and the terminal report never drift apart."""
    fl = [f.asdict() for f in findings]
    return json.dumps({
        "findings": fl,
        "counts": {sev: sum(1 for f in fl if f["severity"] == sev)
                   for sev in SEVERITIES},
        "suppressed": suppressed,
        "rules": rules_run or [],
        "ok": not fl,
    })


def summary_line(findings: List[Finding], files: int, rules: int,
                 suppressed: int = 0) -> str:
    """bfmonitor-style one-liner: the human-scan summary CI logs end on."""
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    verdict = "clean" if not findings else (
        f"{n_err} error(s), {n_warn} warn(s)")
    return (f"bflint: {rules} rule(s) over {files} file(s): {verdict}"
            f" ({suppressed} baseline-suppressed)")
