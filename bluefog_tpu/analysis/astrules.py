"""AST contract rules: the project's load-bearing conventions as
machine-checked invariants (stdlib ``ast`` only, no new dependencies).

Eleven PRs of review hardening kept re-finding the same drift classes by
hand; each rule below is one of those classes, named and enforced:

``env-doc-drift``
    Every ``BLUEFOG_*`` environment variable the code reads must appear
    in ``docs/env_variable.md``, and every documented name must still be
    read somewhere — catching both the undocumented knob and the stale
    doc row.  Dynamic prefix reads (``_ENV_PREFIX + name`` in the health
    and control threshold tables) count as reading every documented name
    under that prefix.
``jsonl-kind-drift``
    Every record ``kind`` the observability/serving/control exporters
    write must be accepted by ``export.validate_jsonl`` (its
    ``_KIND_REQUIRED`` table), and every accepted kind must still have a
    writer.  Both sets are DERIVED here, never hand-listed, so the
    validator and the exporters cannot drift silently.
``metric-name-drift``
    Every ``bf_*`` counter/gauge/histogram name emitted must appear (by
    exact name — wildcard prose does not count) in ``docs/``, and a name
    must be registered with ONE metric kind everywhere it is used (the
    registry raises on kind aliasing at runtime; this catches it before
    any process runs).
``host-time-in-trace``
    ``time.*`` clocks, ``datetime.now``, ``np.random.*``, and stdlib
    ``random.*`` must be unreachable from functions that get traced
    (passed to ``jax.jit``/``shard_map``/``pmap``, or the step functions
    the ``optim/strategies.py`` builders return): a host-time read inside
    a traced function freezes the first call's value into the compiled
    program — the recompile/replay hazard class.
``knob-outside-cache-key``
    Keyword knobs (parameters with defaults) on the strategy/optimizer/
    train-step factories must either be parameters of
    ``optim/_plumbing.step_cache_key`` or be named in the factory
    module's ``_STEP_KEY_EXEMPT_KNOBS`` annotation (traced data, pinned
    at construction, or keyed via the context ids) — a knob that shapes
    the compiled program but joins neither silently serves stale
    programs.
``import-time-env-read``
    ``os.environ``/``os.getenv`` reads at module import time freeze
    configuration before ``bfrun``/``bf.init()`` can set it; every env
    read must happen inside a function.
``distributed-init-outside-bootstrap``
    ``jax.distributed.initialize`` may only be called from the fleet
    bootstrap module (``bluefog_tpu/fleet/bootstrap.py``): it is
    process-global, once-only, and carries retry/diagnosis semantics
    there — a second call site reintroduces the racy double-init the
    bootstrap path exists to kill.  All import spellings are resolved
    (``jax.distributed.initialize(...)``, ``jd.initialize(...)`` under
    ``import jax.distributed as jd``, bare ``initialize(...)`` under
    ``from jax.distributed import initialize``).

All rules run against a repo root (defaulting to this checkout) so the
analyzer's own tests can run them hermetically on synthetic trees.
"""

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding

__all__ = ["ALL_RULES", "run_ast_rules", "jsonl_kind_sets",
           "emitted_metric_names", "documented_metric_names",
           "default_repo_root"]

ALL_RULES = (
    "env-doc-drift",
    "jsonl-kind-drift",
    "metric-name-drift",
    "host-time-in-trace",
    "knob-outside-cache-key",
    "import-time-env-read",
    "distributed-init-outside-bootstrap",
)

_ENV_NAME = re.compile(r"^BLUEFOG_[A-Z0-9_]*$")
_DOC_ENV_TOKEN = re.compile(r"BLUEFOG_[A-Z0-9_]+")
_DOC_METRIC_TOKEN = re.compile(r"\bbf_[a-z0-9_]+")

# modules whose JSONL writers must agree with validate_jsonl
_JSONL_EXPORTER_DIRS = ("observability", "serving", "control")

# a factory is a function shaped like the step/state builders: a
# build-ish name AND at least two of the canonical knob names in its
# signature (one alone — e.g. a helper taking `compression` — is not a
# factory and carries no cache-key obligation)
_FACTORY_NAME = re.compile(r"^(make_|create_)|(_step|_init|__init__)$")
_KNOB_MARKERS = frozenset({
    "fuse", "fusion_bucket_bytes", "overlap", "telemetry", "compression",
    "control"})
# step_cache_key spells some knobs differently from the factories
_KNOB_ALIASES = {"fusion_bucket_bytes": "bucket_bytes",
                 "backend": "nar_backend",
                 "axis_name": "gossip_axis"}

# host-time hazards (see module docstring).  jax.random is fine — it is
# traced, keyed, and replayable; these are not.
_TIME_FUNCS = frozenset({
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "process_time", "process_time_ns"})
_DATETIME_HAZARDS = frozenset({
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today"})
_JIT_ENTRY_NAMES = frozenset({"jit", "pmap", "pjit", "shard_map"})


def default_repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# per-module fact extraction
# ---------------------------------------------------------------------------

class _ModuleFacts:
    """Everything the rules need from one parsed file."""

    def __init__(self, relpath: str, tree: ast.Module):
        self.relpath = relpath
        self.tree = tree
        self.consts: Dict[str, str] = {}       # module-level str constants
        self.import_map: Dict[str, str] = {}   # local name -> dotted module
        self.env_reads: List[Tuple[str, bool, int, bool]] = []
        #                 (name-or-prefix, is_prefix, line, module_level)
        self.env_literals: Set[str] = set()    # exact BLUEFOG_* constants
        self.env_literal_prefixes: Set[str] = set()
        self.metric_calls: List[Tuple[str, str, int]] = []  # (kind, name, ln)
        self.kind_emits: List[Tuple[str, int]] = []
        self.exempt_knobs: Set[str] = set()    # _STEP_KEY_EXEMPT_KNOBS
        self.functions: Dict[str, ast.FunctionDef] = {}  # name -> def (any)


def _dotted(node) -> Optional[List[str]]:
    """Attribute/Name chain as a name list, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _resolve_str(node, consts: Dict[str, str]
                 ) -> Optional[Tuple[str, bool]]:
    """``(value, is_prefix)`` of a string-ish expression: a literal, a
    module constant, ``PREFIX + x``, or an f-string with a literal head."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.Name) and node.id in consts:
        return consts[node.id], False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_str(node.left, consts)
        if left is not None:
            return left[0], True
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if (isinstance(head, ast.Constant)
                and isinstance(head.value, str)):
            return head.value, True
    return None


def _is_os_environ(node, facts: _ModuleFacts) -> bool:
    """``os.environ`` (or a bare ``environ`` imported from os)."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        root = _dotted(node)
        return bool(root) and facts.import_map.get(root[0]) == "os"
    if isinstance(node, ast.Name):
        return facts.import_map.get(node.id) == "os.environ"
    return False


def _collect_imports(facts: _ModuleFacts) -> None:
    for node in ast.walk(facts.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                facts.import_map[local] = (a.name if a.asname
                                           else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                facts.import_map[a.asname or a.name] = (
                    f"{node.module}.{a.name}")


def _collect_consts(facts: _ModuleFacts) -> None:
    for stmt in facts.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            facts.consts[stmt.targets[0].id] = stmt.value.value


def _collect_exempt_knobs(facts: _ModuleFacts) -> None:
    for stmt in facts.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_STEP_KEY_EXEMPT_KNOBS"):
            for n in ast.walk(stmt.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    facts.exempt_knobs.add(n.value)


def _walk_scoped(node, in_func, visit) -> None:
    """Walk recording whether each node sits inside a function BODY
    (decorators and default expressions evaluate at import time and stay
    module-level)."""
    for child in ast.iter_child_nodes(node):
        child_in = in_func
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if child in node.body:
                child_in = True
        elif isinstance(node, ast.Lambda) and child is node.body:
            child_in = True
        visit(child, child_in)
        _walk_scoped(child, child_in, visit)


def _collect_env_and_metrics(facts: _ModuleFacts) -> None:
    consts = facts.consts

    def note_env(value_prefix, lineno, module_level):
        name, is_prefix = value_prefix
        if not name.startswith("BLUEFOG_"):
            return
        facts.env_reads.append((name, is_prefix, lineno, module_level))

    def visit(node, in_func):
        module_level = not in_func
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _ENV_NAME.match(node.value):
                if node.value.endswith("_"):
                    facts.env_literal_prefixes.add(node.value)
                else:
                    facts.env_literals.add(node.value)
        if isinstance(node, ast.Call):
            func = node.func
            # os.getenv(...) / os.environ.get/pop/setdefault(...)
            if isinstance(func, ast.Attribute):
                recv = func.value
                if (func.attr == "getenv"
                        and isinstance(recv, ast.Name)
                        and facts.import_map.get(recv.id) == "os"):
                    if node.args:
                        r = _resolve_str(node.args[0], consts)
                        if r:
                            note_env(r, node.lineno, module_level)
                            return
                    if module_level:
                        facts.env_reads.append(
                            ("<os.getenv>", True, node.lineno, True))
                elif (func.attr in ("get", "pop", "setdefault")
                        and _is_os_environ(recv, facts)):
                    if node.args:
                        r = _resolve_str(node.args[0], consts)
                        if r:
                            note_env(r, node.lineno, module_level)
                            return
                    if module_level:
                        facts.env_reads.append(
                            ("<os.environ>", True, node.lineno, True))
                elif func.attr == "get" and node.args:
                    # env-dict forwarding reads (`env.get("BLUEFOG_X")`):
                    # count BLUEFOG names only — a generic .get is not an
                    # env read, but launcher env dicts are
                    r = _resolve_str(node.args[0], consts)
                    if r and r[0].startswith("BLUEFOG_"):
                        note_env(r, node.lineno, False)
            elif (isinstance(func, ast.Name)
                    and facts.import_map.get(func.id) == "os.getenv"):
                # `from os import getenv` — same read, bare-name spelling
                if node.args:
                    r = _resolve_str(node.args[0], consts)
                    if r:
                        note_env(r, node.lineno, module_level)
                        return
                if module_level:
                    facts.env_reads.append(
                        ("<os.getenv>", True, node.lineno, True))
            # metric registrations: counter/gauge/histogram("bf_...")
            mkind = None
            if isinstance(func, ast.Attribute) and func.attr in (
                    "counter", "gauge", "histogram"):
                mkind = func.attr
            elif isinstance(func, ast.Name) and func.id in (
                    "counter", "gauge", "histogram"):
                mkind = func.id
            if (mkind and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("bf_")):
                facts.metric_calls.append(
                    (mkind, node.args[0].value, node.lineno))
        elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load):
            if _is_os_environ(node.value, facts):
                r = _resolve_str(node.slice, consts)
                if r:
                    note_env(r, node.lineno, module_level)
                elif module_level:
                    facts.env_reads.append(
                        ("<os.environ>", True, node.lineno, True))
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (isinstance(k, ast.Constant) and k.value == "kind"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    facts.kind_emits.append((v.value, node.lineno))
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Constant)
                        and tgt.slice.value == "kind"
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    facts.kind_emits.append(
                        (node.value.value, node.lineno))

    _walk_scoped(facts.tree, False, visit)


def _collect_functions(facts: _ModuleFacts) -> None:
    for node in ast.walk(facts.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            facts.functions.setdefault(node.name, node)


def _parse_file(root: str, relpath: str) -> Optional[_ModuleFacts]:
    path = os.path.join(root, relpath)
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=relpath)
    except (OSError, SyntaxError):
        return None
    facts = _ModuleFacts(relpath, tree)
    _collect_imports(facts)
    _collect_consts(facts)
    _collect_exempt_knobs(facts)
    _collect_env_and_metrics(facts)
    _collect_functions(facts)
    return facts


def _package_files(root: str) -> List[str]:
    out = []
    pkg = os.path.join(root, "bluefog_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for fn in sorted(files):
            if fn.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(out)


def _extra_env_files(root: str) -> List[str]:
    """bench.py + scripts/: read-scope for the stale-doc direction (a
    documented var whose only reader is the bench harness is not stale)."""
    out = []
    if os.path.exists(os.path.join(root, "bench.py")):
        out.append("bench.py")
    scripts = os.path.join(root, "scripts")
    for dirpath, _dirs, files in os.walk(scripts):
        for fn in sorted(files):
            if fn.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return out


# ---------------------------------------------------------------------------
# rule: env-doc-drift + import-time-env-read
# ---------------------------------------------------------------------------

def _doc_env_names(root: str) -> Tuple[Set[str], Set[str], Dict[str, int]]:
    """(exact documented names, documented prefixes, name -> first line)."""
    path = os.path.join(root, "docs", "env_variable.md")
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    first_line: Dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                for tok in _DOC_ENV_TOKEN.findall(line):
                    first_line.setdefault(tok, lineno)
                    if tok.endswith("_"):
                        prefixes.add(tok)
                    else:
                        exact.add(tok)
    except OSError:
        pass
    return exact, prefixes, first_line


def _rule_env_doc_drift(root, pkg_facts, extra_facts) -> List[Finding]:
    documented, doc_prefixes, doc_lines = _doc_env_names(root)
    findings: List[Finding] = []
    read_names: Set[str] = set()
    read_prefixes: Set[str] = set()
    use_names: Set[str] = set()
    use_prefixes: Set[str] = set()
    for facts in pkg_facts + extra_facts:
        use_names |= facts.env_literals
        use_prefixes |= facts.env_literal_prefixes
        for name, is_prefix, _ln, _ml in facts.env_reads:
            if name.startswith("<"):
                continue
            (read_prefixes if is_prefix or name.endswith("_")
             else read_names).add(name)
    # direction A: every strict read in the package (and bench.py) must
    # be documented
    for facts in pkg_facts + [f for f in extra_facts
                              if f.relpath == "bench.py"]:
        for name, is_prefix, lineno, _ml in facts.env_reads:
            if name.startswith("<"):
                continue
            if is_prefix or name.endswith("_"):
                if not any(d.startswith(name) for d in documented):
                    findings.append(Finding(
                        "env-doc-drift", "error", facts.relpath, lineno,
                        f"dynamic env read with prefix {name!r} matches "
                        f"no documented BLUEFOG_* name in "
                        f"docs/env_variable.md"))
            elif name not in documented:
                findings.append(Finding(
                    "env-doc-drift", "error", facts.relpath, lineno,
                    f"env var {name!r} is read here but not documented "
                    f"in docs/env_variable.md"))
    # direction B: every documented name must still be used in code
    for name in sorted(documented):
        used = (name in use_names or name in read_names
                or any(name.startswith(p)
                       for p in read_prefixes | use_prefixes))
        if not used:
            findings.append(Finding(
                "env-doc-drift", "warn", "docs/env_variable.md",
                doc_lines.get(name, 1),
                f"documented env var {name!r} is read nowhere in "
                f"bluefog_tpu/, bench.py, or scripts/ — stale doc row?"))
    for prefix in sorted(doc_prefixes):
        covered = (prefix in read_prefixes or prefix in use_prefixes
                   or any(n.startswith(prefix)
                          for n in use_names | read_names))
        if not covered:
            findings.append(Finding(
                "env-doc-drift", "warn", "docs/env_variable.md",
                doc_lines.get(prefix, 1),
                f"documented env prefix {prefix!r} matches no code read"))
    return findings


def _rule_import_time_env_read(pkg_facts) -> List[Finding]:
    findings = []
    for facts in pkg_facts:
        for name, _is_prefix, lineno, module_level in facts.env_reads:
            if module_level:
                shown = name if not name.startswith("<") else "environment"
                findings.append(Finding(
                    "import-time-env-read", "error", facts.relpath, lineno,
                    f"{shown} is read at module import time — this "
                    f"freezes config before bfrun/bf.init() can set it; "
                    f"move the read inside a function"))
    return findings


# ---------------------------------------------------------------------------
# rule: distributed-init-outside-bootstrap
# ---------------------------------------------------------------------------

# the single allowed call site of jax.distributed.initialize
_BOOTSTRAP_RELPATH = "bluefog_tpu/fleet/bootstrap.py"
_DISTRIBUTED_INIT = "jax.distributed.initialize"


def _rule_distributed_init_outside_bootstrap(pkg_facts) -> List[Finding]:
    findings = []
    for facts in pkg_facts:
        if facts.relpath.replace(os.sep, "/") == _BOOTSTRAP_RELPATH:
            continue
        for node in ast.walk(facts.tree):
            if not isinstance(node, ast.Call):
                continue
            parts = _dotted(node.func)
            if not parts:
                continue
            head = facts.import_map.get(parts[0], parts[0])
            dotted = ".".join([head] + parts[1:])
            if dotted == _DISTRIBUTED_INIT:
                findings.append(Finding(
                    "distributed-init-outside-bootstrap", "error",
                    facts.relpath, node.lineno,
                    f"jax.distributed.initialize called outside "
                    f"{_BOOTSTRAP_RELPATH} — the fleet bootstrap is the "
                    f"single bring-up path (retry, diagnosis, once-only "
                    f"guard); route through "
                    f"bluefog_tpu.fleet.bootstrap.ensure_initialized"))
    return findings


# ---------------------------------------------------------------------------
# rule: jsonl-kind-drift
# ---------------------------------------------------------------------------

def _accepted_kinds(pkg_facts) -> Tuple[Set[str], str, Dict[str, int]]:
    """Kinds ``validate_jsonl`` accepts, derived from the
    ``_KIND_REQUIRED`` table in observability/export.py."""
    accepted: Set[str] = set()
    src = ""
    lines: Dict[str, int] = {}
    for facts in pkg_facts:
        if not facts.relpath.replace(os.sep, "/").endswith(
                "observability/export.py"):
            continue
        src = facts.relpath
        for stmt in facts.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_KIND_REQUIRED"
                    and isinstance(stmt.value, ast.Dict)):
                for k in stmt.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        accepted.add(k.value)
                        lines[k.value] = k.lineno
    return accepted, src, lines


def _emitted_kinds(pkg_facts) -> Dict[str, Tuple[str, int]]:
    emitted: Dict[str, Tuple[str, int]] = {}
    for facts in pkg_facts:
        parts = facts.relpath.replace(os.sep, "/").split("/")
        if len(parts) < 3 or parts[1] not in _JSONL_EXPORTER_DIRS:
            continue
        for kind, lineno in facts.kind_emits:
            emitted.setdefault(kind, (facts.relpath, lineno))
    return emitted


def _rule_jsonl_kind_drift(pkg_facts) -> List[Finding]:
    accepted, validator_path, accepted_lines = _accepted_kinds(pkg_facts)
    emitted = _emitted_kinds(pkg_facts)
    findings = []
    if not validator_path:
        return findings
    for kind, (path, lineno) in sorted(emitted.items()):
        if kind not in accepted:
            findings.append(Finding(
                "jsonl-kind-drift", "error", path, lineno,
                f"JSONL record kind {kind!r} is written here but "
                f"validate_jsonl (_KIND_REQUIRED) does not accept it"))
    for kind in sorted(accepted - set(emitted)):
        findings.append(Finding(
            "jsonl-kind-drift", "warn", validator_path,
            accepted_lines.get(kind, 1),
            f"validate_jsonl accepts kind {kind!r} but no exporter under "
            f"{'/'.join(_JSONL_EXPORTER_DIRS)} writes it — stale "
            f"validator entry?"))
    return findings


# ---------------------------------------------------------------------------
# rule: metric-name-drift
# ---------------------------------------------------------------------------

def _doc_metric_names(root: str) -> Set[str]:
    names: Set[str] = set()
    docs = os.path.join(root, "docs")
    try:
        entries = sorted(os.listdir(docs))
    except OSError:
        return names
    for fn in entries:
        if not fn.endswith(".md"):
            continue
        try:
            with open(os.path.join(docs, fn), encoding="utf-8") as f:
                names.update(_DOC_METRIC_TOKEN.findall(f.read()))
        except OSError:
            pass
    return names


def _rule_metric_name_drift(root, pkg_facts) -> List[Finding]:
    documented = _doc_metric_names(root)
    findings = []
    kinds_by_name: Dict[str, Dict[str, Tuple[str, int]]] = {}
    for facts in pkg_facts:
        for mkind, name, lineno in facts.metric_calls:
            kinds_by_name.setdefault(name, {}).setdefault(
                mkind, (facts.relpath, lineno))
            if name not in documented:
                findings.append(Finding(
                    "metric-name-drift", "error", facts.relpath, lineno,
                    f"metric {name!r} ({mkind}) is emitted here but its "
                    f"exact name appears nowhere in docs/ (wildcard "
                    f"prose like '{name.rsplit('_', 1)[0]}_*' does not "
                    f"count)"))
    for name, kinds in sorted(kinds_by_name.items()):
        if len(kinds) > 1:
            sites = ", ".join(
                f"{k} at {p}:{ln}" for k, (p, ln) in sorted(kinds.items()))
            path, lineno = sorted(kinds.values())[0]
            findings.append(Finding(
                "metric-name-drift", "error", path, lineno,
                f"metric {name!r} is registered with conflicting kinds "
                f"({sites}) — the registry raises on this at runtime"))
    return findings


# ---------------------------------------------------------------------------
# rule: host-time-in-trace
# ---------------------------------------------------------------------------

def _traced_functions(facts: _ModuleFacts) -> Set[ast.AST]:
    """Function nodes whose bodies end up inside a traced program."""
    seeds: Set[ast.AST] = set()

    def name_of(node):
        d = _dotted(node)
        return d[-1] if d else None

    for node in ast.walk(facts.tree):
        if isinstance(node, ast.Call) and name_of(node.func) in \
                _JIT_ENTRY_NAMES and node.args:
            arg = node.args[0]
            if isinstance(arg, (ast.Lambda,)):
                seeds.add(arg)
            elif isinstance(arg, ast.Name) and arg.id in facts.functions:
                seeds.add(facts.functions[arg.id])
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if name_of(target) in _JIT_ENTRY_NAMES:
                    seeds.add(node)
                elif (isinstance(dec, ast.Call)
                        and name_of(dec.func) == "partial"):
                    for a in dec.args:
                        if name_of(a) in _JIT_ENTRY_NAMES:
                            seeds.add(node)
    # optimizer step builders: the nested functions a top-level `*_step`
    # builder closes over ARE the traced step cores, even though the
    # jax.jit call happens a module away (optim/wrappers.py, training.py)
    for stmt in facts.tree.body:
        if (isinstance(stmt, ast.FunctionDef)
                and stmt.name.endswith("_step")):
            for inner in ast.walk(stmt):
                if inner is not stmt and isinstance(
                        inner, (ast.FunctionDef, ast.Lambda)):
                    seeds.add(inner)

    # transitive closure over same-module calls + nested defs
    traced: Set[ast.AST] = set()
    frontier = list(seeds)
    while frontier:
        fn = frontier.pop()
        if fn in traced:
            continue
        traced.add(fn)
        for inner in ast.walk(fn):
            if inner is not fn and isinstance(
                    inner, (ast.FunctionDef, ast.Lambda)):
                if inner not in traced:
                    frontier.append(inner)
            if isinstance(inner, ast.Call) and isinstance(
                    inner.func, ast.Name):
                callee = facts.functions.get(inner.func.id)
                if callee is not None and callee not in traced:
                    frontier.append(callee)
    return traced


def _hazard_call(node: ast.Call, facts: _ModuleFacts) -> Optional[str]:
    chain = _dotted(node.func)
    if not chain:
        return None
    root_module = facts.import_map.get(chain[0])
    if root_module is None:
        return None
    full = ".".join([root_module] + chain[1:])
    if root_module == "time" and len(chain) == 2 and \
            chain[1] in _TIME_FUNCS:
        return full
    if root_module in ("time.time", "time.perf_counter", "time.monotonic",
                       "time.time_ns") and len(chain) == 1:
        return root_module
    if full in _DATETIME_HAZARDS or root_module in _DATETIME_HAZARDS:
        return full
    if full.startswith("numpy.random.") or root_module == "numpy.random":
        return full
    if root_module == "random" and len(chain) >= 2:
        return full
    if root_module.startswith("random.") and len(chain) == 1:
        return root_module
    return None


def _rule_host_time_in_trace(pkg_facts) -> List[Finding]:
    findings = []
    for facts in pkg_facts:
        traced = _traced_functions(facts)
        if not traced:
            continue
        seen_lines: Set[int] = set()
        for fn in traced:
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    # nested defs are traversed via their own traced entry
                    if isinstance(node, ast.Call):
                        hazard = _hazard_call(node, facts)
                        if hazard and node.lineno not in seen_lines:
                            seen_lines.add(node.lineno)
                            findings.append(Finding(
                                "host-time-in-trace", "error",
                                facts.relpath, node.lineno,
                                f"{hazard}() is reachable inside a traced "
                                f"function — the first call's host value "
                                f"freezes into the compiled program "
                                f"(recompile/replay hazard); hoist it to "
                                f"the host loop or use jax.random"))
        _ = traced
    return findings


# ---------------------------------------------------------------------------
# rule: knob-outside-cache-key
# ---------------------------------------------------------------------------

def _cache_key_params(pkg_facts) -> Set[str]:
    for facts in pkg_facts:
        if not facts.relpath.replace(os.sep, "/").endswith(
                "optim/_plumbing.py"):
            continue
        fn = facts.functions.get("step_cache_key")
        if fn is None:
            continue
        names = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        names.discard("cx")
        names.discard("params")
        return names
    return set()


def _rule_knob_outside_cache_key(pkg_facts) -> List[Finding]:
    key_params = _cache_key_params(pkg_facts)
    if not key_params:
        return []
    findings = []
    for facts in pkg_facts:
        used_exemptions: Set[str] = set()
        for node in ast.walk(facts.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if not _FACTORY_NAME.search(node.name):
                continue
            all_params = [a.arg for a in node.args.args
                          + node.args.kwonlyargs]
            if len(set(all_params) & _KNOB_MARKERS) < 2:
                continue
            # params with defaults = the keyword knobs
            pos = node.args.args
            defaulted = [a.arg for a in
                         pos[len(pos) - len(node.args.defaults):]]
            defaulted += [a.arg for a, d in
                          zip(node.args.kwonlyargs, node.args.kw_defaults)
                          if d is not None]
            for knob in defaulted:
                if knob in ("self", "cls"):
                    continue
                normalized = _KNOB_ALIASES.get(knob, knob)
                if normalized in key_params or knob in key_params:
                    continue
                if knob in facts.exempt_knobs:
                    used_exemptions.add(knob)
                    continue
                findings.append(Finding(
                    "knob-outside-cache-key", "error", facts.relpath,
                    node.lineno,
                    f"factory {node.name}() keyword knob {knob!r} is "
                    f"neither a step_cache_key parameter nor listed in "
                    f"this module's _STEP_KEY_EXEMPT_KNOBS — a knob that "
                    f"shapes the compiled step but joins neither would "
                    f"silently serve stale programs"))
        # stale exemptions get the baseline treatment: a name that no
        # longer matches any factory knob silently pre-exempts whatever
        # future knob reuses it — the exact hazard the rule exists for
        for dead in sorted(facts.exempt_knobs - used_exemptions):
            findings.append(Finding(
                "knob-outside-cache-key", "warn", facts.relpath, 1,
                f"_STEP_KEY_EXEMPT_KNOBS entry {dead!r} matches no "
                f"keyword knob on any factory in this module — delete "
                f"the dead exemption"))
    return findings


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def _load_facts(root: str) -> Tuple[List[_ModuleFacts], List[_ModuleFacts]]:
    pkg = [f for f in (_parse_file(root, p) for p in _package_files(root))
           if f is not None]
    extra = [f for f in (_parse_file(root, p)
                         for p in _extra_env_files(root)) if f is not None]
    return pkg, extra


def run_ast_rules(repo_root: Optional[str] = None,
                  rules: Optional[List[str]] = None
                  ) -> Tuple[List[Finding], int]:
    """Run the selected (default: all) AST rules over ``repo_root``.
    Returns ``(findings, files_scanned)`` with findings sorted by
    location for stable output."""
    root = repo_root or default_repo_root()
    selected = set(rules or ALL_RULES)
    unknown = selected - set(ALL_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)} "
                         f"(known: {list(ALL_RULES)})")
    pkg_facts, extra_facts = _load_facts(root)
    findings: List[Finding] = []
    if "env-doc-drift" in selected:
        findings += _rule_env_doc_drift(root, pkg_facts, extra_facts)
    if "import-time-env-read" in selected:
        findings += _rule_import_time_env_read(pkg_facts)
    if "distributed-init-outside-bootstrap" in selected:
        findings += _rule_distributed_init_outside_bootstrap(pkg_facts)
    if "jsonl-kind-drift" in selected:
        findings += _rule_jsonl_kind_drift(pkg_facts)
    if "metric-name-drift" in selected:
        findings += _rule_metric_name_drift(root, pkg_facts)
    if "host-time-in-trace" in selected:
        findings += _rule_host_time_in_trace(pkg_facts)
    if "knob-outside-cache-key" in selected:
        findings += _rule_knob_outside_cache_key(pkg_facts)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, len(pkg_facts) + len(extra_facts)


def jsonl_kind_sets(repo_root: Optional[str] = None
                    ) -> Tuple[Set[str], Set[str]]:
    """``(emitted, accepted)`` record-kind sets, both analyzer-derived —
    the cross-check test asserts equality so neither can drift."""
    pkg_facts, _ = _load_facts(repo_root or default_repo_root())
    accepted, _path, _lines = _accepted_kinds(pkg_facts)
    return set(_emitted_kinds(pkg_facts)), accepted


def emitted_metric_names(repo_root: Optional[str] = None
                         ) -> Dict[str, Set[str]]:
    """metric name -> set of kinds it is registered with."""
    pkg_facts, _ = _load_facts(repo_root or default_repo_root())
    out: Dict[str, Set[str]] = {}
    for facts in pkg_facts:
        for mkind, name, _ln in facts.metric_calls:
            out.setdefault(name, set()).add(mkind)
    return out


def documented_metric_names(repo_root: Optional[str] = None) -> Set[str]:
    return _doc_metric_names(repo_root or default_repo_root())
