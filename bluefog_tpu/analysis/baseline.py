"""Baseline suppression file for ``bflint`` findings.

``analysis/baseline.toml`` is the checked-in list of findings the project
has explicitly decided to carry (it ships EMPTY: real findings get fixed,
not suppressed — a suppression is a documented debt, not a convenience).
Python 3.10 has no ``tomllib``, and the hard no-new-deps constraint rules
out a TOML package, so this module parses the small TOML subset the
baseline format needs:

.. code-block:: toml

    # why this entry exists (reviewed like code)
    [[suppress]]
    rule = "host-time-in-trace"        # required: rule id, or "*"
    path = "bluefog_tpu/foo/bar.py"    # required: repo-relative fnmatch glob
    line = 120                          # optional: pin to a line
    message = "time.time"              # optional: substring of the message
    reason = "host callback, reviewed 2026-08-04"  # required: the why

Matching: a finding is suppressed by the FIRST entry whose rule, path
glob, optional line, and optional message substring all match.  Entries
that never matched anything are themselves reported (a stale suppression
hides nothing and should be deleted) — returned by :func:`apply` so the
CLI can surface them.
"""

import fnmatch
import os
import re
from typing import Dict, List, Tuple

from .findings import Finding

__all__ = ["BaselineError", "load_baseline", "apply", "DEFAULT_PATH"]

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "baseline.toml")

_KV = re.compile(r"^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(.+?)\s*$")


class BaselineError(ValueError):
    """Malformed baseline file — always fatal: a suppression that fails
    to parse must not silently suppress nothing (or everything)."""


def _parse_value(raw: str, path: str, lineno: int):
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        body = raw[1:-1]
        # the only escapes the format needs; anything fancier is a smell
        return body.replace('\\"', '"').replace("\\\\", "\\")
    if raw in ("true", "false"):
        return raw == "true"
    if re.fullmatch(r"-?[0-9]+", raw):
        return int(raw)
    raise BaselineError(
        f"{path}:{lineno}: unsupported TOML value {raw!r} (the baseline "
        f"subset takes quoted strings, integers, and booleans)")


def _strip_comment(line: str) -> str:
    """Drop a trailing ``#`` comment, respecting double-quoted strings."""
    out, in_str = [], False
    i = 0
    while i < len(line):
        c = line[i]
        if c == '"' and (i == 0 or line[i - 1] != "\\"):
            in_str = not in_str
        if c == "#" and not in_str:
            break
        out.append(c)
        i += 1
    return "".join(out).strip()


def load_baseline(path: str = DEFAULT_PATH) -> List[Dict]:
    """Parse the baseline file into a list of suppression dicts.

    A missing file reads as empty (the seeded state); a present but
    malformed file raises :class:`BaselineError`."""
    if not os.path.exists(path):
        return []
    entries: List[Dict] = []
    current = None
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = _strip_comment(raw)
            if not line:
                continue
            if line == "[[suppress]]":
                current = {"_line": lineno}
                entries.append(current)
                continue
            if line.startswith("["):
                raise BaselineError(
                    f"{path}:{lineno}: unknown table {line!r} (only "
                    f"[[suppress]] entries are understood)")
            m = _KV.match(line)
            if not m:
                raise BaselineError(
                    f"{path}:{lineno}: unparseable line {line!r}")
            if current is None:
                raise BaselineError(
                    f"{path}:{lineno}: key outside a [[suppress]] table")
            current[m.group(1)] = _parse_value(m.group(2), path, lineno)
    for e in entries:
        for req in ("rule", "path", "reason"):
            if req not in e:
                raise BaselineError(
                    f"{path}:{e['_line']}: [[suppress]] entry missing "
                    f"required key {req!r}")
    return entries


def _matches(entry: Dict, finding: Finding) -> bool:
    if entry["rule"] not in ("*", finding.rule):
        return False
    if not fnmatch.fnmatch(finding.path, entry["path"]):
        return False
    if "line" in entry and entry["line"] != finding.line:
        return False
    if "message" in entry and entry["message"] not in finding.message:
        return False
    return True


def apply(findings: List[Finding], entries: List[Dict]
          ) -> Tuple[List[Finding], int, List[Dict]]:
    """``(kept, suppressed_count, stale_entries)``: filter findings
    through the baseline; entries that matched nothing come back as
    stale (the CLI reports them so dead suppressions get deleted)."""
    kept: List[Finding] = []
    used = [False] * len(entries)
    suppressed = 0
    for f in findings:
        hit = False
        for i, e in enumerate(entries):
            if _matches(e, f):
                used[i] = True
                hit = True
                break
        if hit:
            suppressed += 1
        else:
            kept.append(f)
    stale = [e for i, e in enumerate(entries) if not used[i]]
    return kept, suppressed, stale
