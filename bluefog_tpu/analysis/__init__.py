"""Project-invariant static analysis (``bflint``).

Two halves (docs/static_analysis.md):

* :mod:`.astrules` — AST contract rules over the package source: env-var
  doc sync, JSONL kind sync, metric-name registration, host-time-in-
  trace, step-cache-key knob coverage, import-time env reads.
* :mod:`.tracehazards` — StableHLO trace-hazard pass over the lowered
  canonical step programs: dropped buffer donation, wire dtype upcasts,
  collective count vs the fusion-plan budget.  (Imported lazily — it
  pulls in jax; the AST half stays import-light so ``bflint`` can pin
  the CPU platform before any backend initializes.)

Findings filter through the checked-in ``analysis/baseline.toml``
(seeded empty — fix findings, do not suppress them) and gate
``make lint`` and ``tests/test_lint_clean.py``.
"""

from .astrules import (ALL_RULES, documented_metric_names,
                       emitted_metric_names, jsonl_kind_sets,
                       run_ast_rules)
from .baseline import BaselineError, load_baseline
from .findings import Finding, format_json, format_text, summary_line

__all__ = [
    "ALL_RULES", "Finding", "run_ast_rules", "jsonl_kind_sets",
    "emitted_metric_names", "documented_metric_names", "load_baseline",
    "BaselineError", "format_text", "format_json", "summary_line",
]
