"""Runtime context: device mesh, topology state, and rank queries.

TPU-native replacement for the reference's global state + C ``bluefog_*`` API
(``bluefog/common/global_state.h``, ``operations.cc:1215-1402``,
``bluefog/common/basics.py``).  There is no background thread or coordinator:
state is a device mesh plus compiled topology schedules; every op is a jitted
SPMD program over the mesh.

"Machine" structure (reference local/cross communicators,
``mpi_context.cc:322-345``) maps to a 2-D ``(machine, local)`` mesh whose
``local`` axis should align with ICI and ``machine`` with DCN on multi-host
pods.  On a single host the split can be simulated with
``BLUEFOG_NODES_PER_MACHINE`` exactly like the reference simulates multi-node
on localhost (``mpi_context.cc:26,322``).
"""

import logging
import os
import threading
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np
import networkx as nx

from .parallel import topology as topology_util
from .parallel.schedule import (
    CompiledTopology,
    DynamicSchedule,
    compile_topology,
)

logger = logging.getLogger("bluefog_tpu")

_RANK_AXIS = "rank"
_MACHINE_AXIS = "machine"
_LOCAL_AXIS = "local"


class BlueFogContext:
    """Holds the mesh and the (machine) topology, analogous to
    ``BluefogGlobalState`` (global_state.h:44-117) minus all the threading."""

    def __init__(self,
                 devices: Optional[Sequence] = None,
                 nodes_per_machine: Optional[int] = None):
        self._devices = list(devices) if devices is not None else list(jax.devices())
        self._size = len(self._devices)

        expected = os.environ.get("BLUEFOG_EXPECTED_SIZE")
        if expected is not None and devices is None and int(expected) != self._size:
            raise RuntimeError(
                f"bfrun requested -np {expected} devices but the runtime "
                f"found {self._size}; fix -np, add --platform cpu for "
                f"virtual devices, or unset BLUEFOG_EXPECTED_SIZE")

        if nodes_per_machine is None:
            env = os.environ.get("BLUEFOG_NODES_PER_MACHINE")
            if env is not None:
                nodes_per_machine = int(env)
            elif jax.process_count() > 1:
                nodes_per_machine = max(1, self._size // jax.process_count())
            else:
                nodes_per_machine = self._size
        if self._size % nodes_per_machine != 0:
            raise ValueError(
                f"size {self._size} not divisible by nodes_per_machine "
                f"{nodes_per_machine}")
        self._local_size = nodes_per_machine

        # fleet identity: which OS process this controller is, and which
        # device slots it owns (stamped for the fleet supervisor / the
        # per-process routers; single-process runs get 0 / all slots)
        self.process_index = int(jax.process_index())
        self.local_device_ids = [
            i for i, d in enumerate(self._devices)
            if getattr(d, "process_index", 0) == self.process_index]

        dev_array = np.asarray(self._devices)
        self.mesh = jax.sharding.Mesh(dev_array, (_RANK_AXIS,))
        self.mesh_2d = jax.sharding.Mesh(
            dev_array.reshape(self.machine_size, self._local_size),
            (_MACHINE_AXIS, _LOCAL_AXIS))

        self._topology: Optional[nx.DiGraph] = None
        self._compiled: Optional[CompiledTopology] = None
        self._is_topo_weighted = False
        self._machine_topology: Optional[nx.DiGraph] = None
        self._compiled_machine: Optional[CompiledTopology] = None
        self._is_machine_topo_weighted = False
        # suspend/resume gate: ops wait on this event before dispatching
        # (set = running).  Reference parity: bluefog_suspend/resume pause
        # the background op loop (operations.cc:1392-1400) so a notebook
        # can halt traffic mid-run; here the dispatch points block instead.
        self._resume_event = threading.Event()
        self._resume_event.set()

    @property
    def suspended(self) -> bool:
        return not self._resume_event.is_set()

    def wait_if_suspended(self) -> None:
        """Block the calling thread while suspended (no-op when running).

        Called at every op-dispatch boundary BEFORE any tracing/dispatch
        (collectives via the ``_suspend_gated`` decorator in ``ops/api.py``,
        windows via ``_dispatch_win_op``).  ``resume()`` from another thread
        (the notebook/driver) releases all waiters, like the reference's
        condition-variable wakeup."""
        if self._resume_event.is_set():
            return
        logger.debug("bluefog op dispatch paused by suspend(); waiting")
        self._resume_event.wait()

    # -- size / rank queries (basics.py:78-145) -----------------------------

    @property
    def size(self) -> int:
        return self._size

    @property
    def local_size(self) -> int:
        return self._local_size

    @property
    def machine_size(self) -> int:
        return self._size // self._local_size

    @property
    def rank_axis(self) -> str:
        return _RANK_AXIS

    @property
    def machine_axis(self) -> str:
        return _MACHINE_AXIS

    @property
    def local_axis(self) -> str:
        return _LOCAL_AXIS

    def rank(self) -> int:
        """Controller rank.  A single-controller SPMD program drives all
        devices at once, so per-rank API queries take an explicit ``rank``
        argument; this returns the first device index owned by this process
        (0 on a single host) for reference-compatible call sites."""
        if jax.process_count() > 1:
            for i, d in enumerate(self._devices):
                if d.process_index == jax.process_index():
                    return i
        return 0

    def local_rank(self) -> int:
        return self.rank() % self._local_size

    def machine_rank(self, rank: Optional[int] = None) -> int:
        r = self.rank() if rank is None else rank
        return r // self._local_size

    def is_homogeneous(self) -> bool:
        return True

    # -- topology (basics.py:311-419) ---------------------------------------

    def set_topology(self, topo: Optional[nx.DiGraph] = None,
                     is_weighted: bool = False) -> bool:
        from .ops import windows as _win  # local import; windows imports context
        if _win.windows_exist():
            raise RuntimeError(
                "cannot change the topology while windows exist; free them "
                "first (reference operations.cc:1286-1311)")
        if topo is None:
            topo = topology_util.ExponentialGraph(self._size)
        if topo.number_of_nodes() != self._size:
            raise ValueError(
                f"topology has {topo.number_of_nodes()} nodes but the mesh "
                f"has {self._size} devices")
        self._topology = topo
        self._is_topo_weighted = is_weighted
        self._compiled = compile_topology(
            topo if is_weighted else _uniform_weights(topo))
        return True

    def set_machine_topology(self, topo: nx.DiGraph,
                             is_weighted: bool = False) -> bool:
        if topo.number_of_nodes() != self.machine_size:
            raise ValueError(
                f"machine topology has {topo.number_of_nodes()} nodes but "
                f"there are {self.machine_size} machines")
        self._machine_topology = topo
        self._is_machine_topo_weighted = is_weighted
        self._compiled_machine = compile_topology(
            topo if is_weighted else _uniform_weights(topo))
        return True

    def load_topology(self) -> Optional[nx.DiGraph]:
        return self._topology

    def load_machine_topology(self) -> Optional[nx.DiGraph]:
        return self._machine_topology

    def is_topo_weighted(self) -> bool:
        return self._is_topo_weighted

    def is_machine_topo_weighted(self) -> bool:
        return self._is_machine_topo_weighted

    @property
    def compiled_topology(self) -> CompiledTopology:
        if self._compiled is None:
            raise RuntimeError("BlueFog TPU has not been initialized; call bf.init()")
        return self._compiled

    @property
    def compiled_machine_topology(self) -> CompiledTopology:
        if self._compiled_machine is None:
            raise RuntimeError("machine topology not set; call bf.set_machine_topology()")
        return self._compiled_machine

    def in_neighbor_ranks(self, rank: Optional[int] = None) -> List[int]:
        if self._topology is None:
            return []
        r = self.rank() if rank is None else rank
        return [s for s in self._topology.predecessors(r) if s != r]

    def out_neighbor_ranks(self, rank: Optional[int] = None) -> List[int]:
        if self._topology is None:
            return []
        r = self.rank() if rank is None else rank
        return [s for s in self._topology.successors(r) if s != r]

    def in_neighbor_machine_ranks(self, rank: Optional[int] = None) -> List[int]:
        if self._machine_topology is None:
            return []
        m = self.machine_rank(rank)
        return [s for s in self._machine_topology.predecessors(m) if s != m]

    def out_neighbor_machine_ranks(self, rank: Optional[int] = None) -> List[int]:
        if self._machine_topology is None:
            return []
        m = self.machine_rank(rank)
        return [s for s in self._machine_topology.successors(m) if s != m]

    # -- misc toggles (basics.py:441-454,548-568) ---------------------------

    def suspend(self):
        """Pause op dispatch: subsequent collective/window calls block at
        their dispatch point until :meth:`resume` (reference
        ``bluefog_suspend``, operations.cc:1392-1396)."""
        self._resume_event.clear()

    def resume(self):
        """Release all threads blocked by :meth:`suspend` (reference
        ``bluefog_resume``, operations.cc:1397-1400)."""
        self._resume_event.set()


def _uniform_weights(topo: nx.DiGraph) -> nx.DiGraph:
    """Replace topology weights with the uniform 1/(in_degree+1) rule used
    when ``is_weighted=False`` (reference torch/mpi_ops.py:506-512)."""
    n = topo.number_of_nodes()
    A = (nx.to_numpy_array(topo) != 0).astype(np.float64)
    np.fill_diagonal(A, 1.0)
    A /= A.sum(axis=0)[None, :]
    return nx.from_numpy_array(A, create_using=nx.DiGraph)


# ---------------------------------------------------------------------------
# Module-level singleton, mirroring the reference's process-global state
# ---------------------------------------------------------------------------

_context: Optional[BlueFogContext] = None
_jax_distributed_started = False


def _maybe_init_jax_distributed(fleet=None):
    """Join the multi-host job set up by ``bfrun`` — the launcher wires
    the coordinator env per host; the reference reaches the same point
    through mpirun's rank env.

    The actual bring-up — env resolution, retry/backoff, NIC pinning,
    the benign already-initialized filter — lives in
    :mod:`bluefog_tpu.fleet.bootstrap`, the package's SINGLE
    ``jax.distributed.initialize`` call site (bflint:
    ``distributed-init-outside-bootstrap``).  This wrapper only keeps
    the historic env + module-flag guard semantics: a no-op with no
    coordinator configured, idempotent across calls.  It must not touch
    any backend-initializing JAX API first.  Returns the bootstrap's
    structured diagnosis record (or ``None`` on the no-op path).
    """
    global _jax_distributed_started
    from .fleet import bootstrap as _bootstrap
    if _jax_distributed_started and fleet is None:
        return None
    spec = _bootstrap.resolve_fleet_spec(fleet)
    if spec is None:
        return None
    diagnosis = _bootstrap.ensure_initialized(spec)
    _jax_distributed_started = _bootstrap.started()
    return diagnosis


def init(topology_fn: Optional[Callable[[int], nx.DiGraph]] = None,
         is_weighted: bool = False,
         devices: Optional[Sequence] = None,
         nodes_per_machine: Optional[int] = None,
         fleet=None) -> BlueFogContext:
    """Initialize the global context (reference ``bf.init``, basics.py:49-70).

    The default topology is an exponential-2 graph over all devices.
    ``fleet`` (a :class:`~bluefog_tpu.fleet.bootstrap.FleetSpec` or
    dict) forces the multi-process bring-up explicitly; with ``None``
    the ``BLUEFOG_FLEET_*`` / legacy coordinator env decides, exactly
    as before (docs/running.md "Fleet mode").
    """
    global _context
    _maybe_init_jax_distributed(fleet)
    _context = BlueFogContext(devices=devices, nodes_per_machine=nodes_per_machine)
    topo = topology_fn(_context.size) if topology_fn else None
    _context.set_topology(topo, is_weighted)
    # BLUEFOG_TIMELINE=<prefix> starts tracing at init, like the reference
    # (operations.cc:464-473 reads the env in the background-thread boot)
    from . import timeline as _tl
    if os.environ.get("BLUEFOG_TIMELINE") and not _tl.timeline_enabled():
        _tl.timeline_start(rank=_context.rank())
    # BLUEFOG_METRICS=<prefix> opens the JSONL metrics sink and enables
    # the host registry the same way (observability/export.py)
    if os.environ.get("BLUEFOG_METRICS"):
        from .observability import export as _export
        if not _export.metrics_active():
            _export.metrics_start(rank=_context.rank())
    return _context


def shutdown() -> None:
    global _context
    from .ops import windows as _win
    from . import timeline as _tl
    from .observability import export as _export
    _win.win_free()
    _win.turn_off_win_ops_with_associated_p()
    _tl.timeline_end()
    _export.metrics_end()
    _context = None


def ctx() -> BlueFogContext:
    if _context is None:
        raise RuntimeError("BlueFog TPU has not been initialized; call bf.init()")
    return _context


def is_initialized() -> bool:
    return _context is not None
