"""Per-process plane gossip: the telemetry plane between OS processes.

The PR 19 plane gossips ``[N, WIRE]`` telemetry rows *inside* one SPMD
program via collective-permutes.  A fleet of real OS processes — each
on its own virtual mesh — has no shared program to permute through, so
:class:`PlanePeer` carries the SAME wire rows over loopback UDP and
merges them with the SAME newest-version-wins rule
(:func:`~bluefog_tpu.observability.plane.host_merge`, the exact
``plane_exchange`` merge factored out for host transports).  Each
process ends up holding a local
:class:`~bluefog_tpu.observability.plane.FleetViewLive`, so its
``RequestRouter`` consumes cross-process liveness/staleness/edge-cost
state through the existing ``observe_plane`` — no shared filesystem,
convergence within the gossip diameter (all-to-all datagrams here:
diameter 1 per poll).

Death detection is purely emergent: a SIGKILLed process stops
publishing, its row's version freezes everywhere, the age
(``step - last_heard``) passes ``BLUEFOG_PLANE_MAX_AGE`` and the row
goes stale → ``alive_mask`` drops it fleet-wide.  A respawned process
calls :meth:`PlanePeer.resume_clock` so its fresh rows republish at a
HIGHER version than its dead incarnation's (the plane's elastic
re-join rule) and win every merge.

Env (docs/env_variable.md "Fleet bring-up"): ``BLUEFOG_FLEET_PEERS``
(``rank=host:port`` comma list), ``BLUEFOG_FLEET_RANK``,
``BLUEFOG_FLEET_SIZE`` — the supervisor exports all three.
"""

import os
import socket
import struct
from typing import Dict, Optional, Tuple

import numpy as np

from ..observability import plane as P
from ..observability import aggregate as AG

__all__ = ["PEERS_ENV", "RANK_ENV", "SIZE_ENV", "parse_peer_map",
           "format_peer_map", "PlanePeer"]

PEERS_ENV = "BLUEFOG_FLEET_PEERS"
RANK_ENV = "BLUEFOG_FLEET_RANK"
SIZE_ENV = "BLUEFOG_FLEET_SIZE"

# datagram: magic, fleet size, effective step, then the [N, WIRE] f32
# table — one row-set per send, merged whole on receive
_MAGIC = 0xB1F0E7
_HEADER = struct.Struct("<III")


def parse_peer_map(text: str) -> Dict[int, Tuple[str, int]]:
    """``"0=127.0.0.1:5000,1=127.0.0.1:5001"`` → rank → (host, port)."""
    peers: Dict[int, Tuple[str, int]] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        rank, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        peers[int(rank)] = (host, int(port))
    return peers


def format_peer_map(peers: Dict[int, Tuple[str, int]]) -> str:
    """Inverse of :func:`parse_peer_map` (supervisor → worker env)."""
    return ",".join(f"{r}={h}:{p}"
                    for r, (h, p) in sorted(peers.items()))


class PlanePeer:
    """One process's plane endpoint: a ``[N, WIRE]`` local table over a
    nonblocking UDP socket.

    Mirrors :class:`~bluefog_tpu.observability.plane.TelemetryPlane`'s
    publish/observe/view surface so consumers can't tell which
    transport fed them; only the exchange differs (datagrams +
    :func:`~bluefog_tpu.observability.plane.host_merge` instead of
    collective-permutes)."""

    def __init__(self, rank: Optional[int] = None,
                 size: Optional[int] = None,
                 peers: Optional[Dict[int, Tuple[str, int]]] = None, *,
                 max_age: Optional[int] = None,
                 window: Optional[int] = None):
        if peers is None:
            text = os.environ.get(PEERS_ENV, "")
            peers = parse_peer_map(text) if text else {}
        if rank is None:
            rank = int(os.environ.get(RANK_ENV, "0"))
        if size is None:
            env_size = os.environ.get(SIZE_ENV)
            size = int(env_size) if env_size else (
                max(peers) + 1 if peers else 1)
        self.rank = int(rank)
        self.size = int(size)
        self.peers = dict(peers)
        self.max_age = P.resolve_max_age(max_age)
        self.window = P.resolve_window(window)
        self.table = np.zeros((self.size, P.WIRE), np.float32)
        self.last_heard = np.zeros((self.size,), np.int64)
        self.step = 0
        self._base = 0              # resume_clock fast-forward offset
        self._records: Dict[int, Dict[int, dict]] = {}
        self._sock: Optional[socket.socket] = None
        if self.rank in self.peers:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
            self._sock.bind(self.peers[self.rank])
            self._sock.setblocking(False)

    # -- clock ---------------------------------------------------------------

    def _eff(self, step: int) -> int:
        return int(step) + self._base

    def eff_step(self, step: int) -> int:
        """The effective (resume-adjusted) plane step for a local step —
        what publishes stamp and what views/ages are measured in."""
        return self._eff(step)

    def resume_clock(self, step: int = 0) -> int:
        """Fast-forward the effective clock past every version already
        circulating (poll first so the table holds the fleet's view of
        the dead incarnation).  The next publish then stamps a strictly
        higher version, so the respawned process's rows win merges
        everywhere — the plane's elastic re-join rule, across OS
        processes.  Returns the new effective step."""
        max_ver = int(self.table[:, P.LANE_VERSION].max())
        want = max(max_ver, self.max_age + 1)
        if self._eff(step) <= want:
            self._base = want - int(step) + 1
        return self._eff(step)

    def chase_clock(self, step: int) -> int:
        """Re-align the effective clock with the freshest OTHER source.
        A one-shot :meth:`resume_clock` is not enough for a respawned
        process: any bring-up stall between the resume and its first
        publish (a compile, a scheduler hiccup) leaves its clock a
        stall's worth of steps behind the fleet FOREVER, and every
        staleness machine keyed on effective steps keeps reading it as
        dead.  Own publishes don't count, so a process that is already
        caught up (or alone) never ratchets itself.  No-op unless
        strictly behind."""
        others = np.delete(self.table[:, P.LANE_VERSION], self.rank)
        if others.size and int(others.max()) > self._eff(step) + 1:
            self._base = int(others.max()) - int(step)
        return self._eff(step)

    # -- exchange ------------------------------------------------------------

    def publish(self, payload, step: int, *, poll: bool = True
                ) -> np.ndarray:
        """Stamp this process's ``[WIDTH]`` payload row (see
        :func:`~bluefog_tpu.observability.plane.pack_payload`) into the
        local table at ``version = step + 1``, datagram the whole table
        to every peer, then (by default) drain + merge what arrived and
        snapshot the view history."""
        eff = self._eff(step)
        row = np.zeros((P.WIRE,), np.float32)
        row[:P.WIDTH] = np.asarray(payload, np.float32)
        row[P.LANE_VERSION] = eff + 1
        row[P.LANE_HOP] = 0.0
        self.table[self.rank] = row
        self.last_heard[self.rank] = eff
        packet = (_HEADER.pack(_MAGIC, self.size, eff)
                  + self.table.tobytes())
        if self._sock is not None:
            for r, addr in self.peers.items():
                if r == self.rank:
                    continue
                try:
                    self._sock.sendto(packet, addr)
                except OSError:
                    pass            # peer gone: death is detected by age
        if poll:
            self.poll(step)
        self.observe(step)
        return self.table

    def poll(self, step: int) -> int:
        """Drain the socket and :func:`host_merge` every received table
        into the local one.  Returns the number of merged datagrams."""
        if self._sock is None:
            return 0
        eff = self._eff(step)
        want = self.size * P.WIRE * 4
        merged = 0
        while True:
            try:
                data, _ = self._sock.recvfrom(_HEADER.size + want + 64)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                break
            if len(data) != _HEADER.size + want:
                continue
            magic, size, _sender_step = _HEADER.unpack_from(data)
            if magic != _MAGIC or size != self.size:
                continue
            received = np.frombuffer(
                data, np.float32, count=self.size * P.WIRE,
                offset=_HEADER.size).reshape(self.size, P.WIRE)
            self.table, self.last_heard = P.host_merge(
                self.table, received, self.last_heard, eff)
            merged += 1
        return merged

    # -- observation (the TelemetryPlane surface) ----------------------------

    def _state(self) -> dict:
        # snapshot()'s [N, N, WIRE] layout with a single local row-set
        return {"table": self.table[None],
                "last_heard": self.last_heard[None]}

    def observe(self, step: int):
        """Snapshot the local table into the rolling per-source history
        (window-bounded, like ``TelemetryPlane.observe``)."""
        self.step = self._eff(step)
        recs = P.snapshot(self._state(), self.step, rank=0,
                          max_age=self.max_age)
        for rec in recs:
            by_step = self._records.setdefault(rec["rank"], {})
            by_step[rec["step"]] = rec
            for old in sorted(by_step)[:-self.window]:
                del by_step[old]
        return recs

    def per_source(self) -> Dict[int, dict]:
        meta = {}
        for rec in P.snapshot(self._state(), self.step, rank=0,
                              max_age=self.max_age):
            meta[rec["rank"]] = {
                "version": rec["plane_version"], "age": rec["plane_age"],
                "hop": rec["plane_hop"], "stale": rec["plane_stale"],
                "step": rec["step"],
            }
        return meta

    def view(self, *, expected_ranks: Optional[int] = None
             ) -> P.FleetViewLive:
        """This process's plane-backed FleetView — hand it straight to
        ``RequestRouter.observe_plane`` / ``health.evaluate``."""
        series = []
        for src in sorted(self._records):
            recs = [self._records[src][s]
                    for s in sorted(self._records[src])]
            series.append(AG.RankSeries(rank=src, records=recs))
        return P.FleetViewLive(series, [], expected_ranks or self.size,
                               self.per_source(), self.step)

    def versions(self) -> np.ndarray:
        """[N] per-source versions in this process's view."""
        return self.table[:, P.LANE_VERSION].copy()

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
