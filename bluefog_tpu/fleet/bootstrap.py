"""Fleet bring-up: THE ``jax.distributed.initialize`` call site.

``bf.init(fleet=...)`` lands here.  The launcher (``bfrun`` multi-host,
or the ``--fleet`` supervisor) wires per-process env; this module
resolves it into a :class:`FleetSpec`, dials the coordinator with
bounded retry/backoff, and stamps the join outcome into a structured
diagnosis record (the bench ladder's skip-record idiom: machine-readable
evidence of WHY a bring-up degraded, not a stack trace in a log).

This is the single bring-up path by contract: bflint's
``distributed-init-outside-bootstrap`` rule rejects any other call to
``jax.distributed.initialize`` in the package, so there is exactly one
place where a process can join (or fail to join) the job — retries,
NIC pinning, and idempotence live here and nowhere else.

Env resolution (``BLUEFOG_FLEET_*`` wins over the legacy names bfrun's
multi-host path exports; docs/env_variable.md "Fleet bring-up"):

=============================  ============================================
``BLUEFOG_FLEET_COORDINATOR``  ``host:port`` (falls back to
                               ``BLUEFOG_COORDINATOR``)
``BLUEFOG_FLEET_NUM_PROCESSES``  job size (falls back to
                               ``BLUEFOG_NUM_PROCESSES``)
``BLUEFOG_FLEET_PROCESS_ID``   this process (falls back to
                               ``BLUEFOG_PROCESS_ID``)
``BLUEFOG_FLEET_CONNECT_RETRIES``  dial attempts (default 3)
``BLUEFOG_FLEET_CONNECT_BACKOFF``  base seconds between attempts,
                               doubling (default 1.0)
``BLUEFOG_FLEET_CONNECT_TIMEOUT``  per-attempt coordinator timeout in
                               seconds (default: the runtime's own)
=============================  ============================================

Works on the CPU backend: ``JAX_PLATFORMS=cpu`` plus
``XLA_FLAGS=--xla_force_host_platform_device_count=K`` gives every
process K virtual devices, so the whole fleet story is CI-testable with
no TPU (docs/running.md "Fleet mode").
"""

import dataclasses
import json
import logging
import os
import time
from typing import Optional

logger = logging.getLogger("bluefog_tpu")

__all__ = ["FleetSpec", "FleetBootstrapError", "resolve_fleet_spec",
           "ensure_initialized", "started", "last_diagnosis",
           "reset_for_testing"]

# set once the runtime joined (or was found already joined): the
# double-call guard bf.init()'s re-entry rides on
_started = False
_last_diagnosis: Optional[dict] = None


@dataclasses.dataclass
class FleetSpec:
    """One process's view of the fleet job: everything
    ``jax.distributed.initialize`` needs, plus the dial policy.

    ``coordinator`` ``None``/empty means "no fleet" — bring-up is a
    no-op and the process runs single-controller (the seed behavior).
    """
    coordinator: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    network_interface: Optional[str] = None
    connect_retries: int = 3
    connect_backoff_s: float = 1.0
    connect_timeout_s: Optional[float] = None

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


class FleetBootstrapError(RuntimeError):
    """The coordinator never answered within the retry budget.  Carries
    the structured ``diagnosis`` record (also banked in
    :func:`last_diagnosis`) so a supervisor or smoke harness can degrade
    loudly instead of parsing an exception string."""

    def __init__(self, diagnosis: dict):
        super().__init__(json.dumps(diagnosis))
        self.diagnosis = diagnosis


def _env(name: str, legacy: Optional[str] = None) -> Optional[str]:
    v = os.environ.get(name)
    if v is None and legacy is not None:
        v = os.environ.get(legacy)
    return v


def resolve_fleet_spec(fleet=None) -> Optional[FleetSpec]:
    """Resolve the bring-up spec: an explicit :class:`FleetSpec` (or
    dict) wins, else the ``BLUEFOG_FLEET_*`` env family with the legacy
    ``BLUEFOG_COORDINATOR`` / ``_NUM_PROCESSES`` / ``_PROCESS_ID``
    names (bfrun's multi-host exports) as fallback.  Returns ``None``
    when no coordinator is configured anywhere — single-process mode."""
    if isinstance(fleet, FleetSpec):
        return fleet
    if isinstance(fleet, dict):
        return FleetSpec(**fleet)
    if fleet is not None:
        raise TypeError(
            f"fleet must be a FleetSpec, a dict, or None, got "
            f"{type(fleet).__name__}")
    coordinator = _env("BLUEFOG_FLEET_COORDINATOR", "BLUEFOG_COORDINATOR")
    if not coordinator:
        return None
    timeout = _env("BLUEFOG_FLEET_CONNECT_TIMEOUT")
    return FleetSpec(
        coordinator=coordinator,
        num_processes=int(_env("BLUEFOG_FLEET_NUM_PROCESSES",
                               "BLUEFOG_NUM_PROCESSES") or 1),
        process_id=int(_env("BLUEFOG_FLEET_PROCESS_ID",
                            "BLUEFOG_PROCESS_ID") or 0),
        network_interface=os.environ.get("BLUEFOG_NETWORK_INTERFACE"),
        connect_retries=int(_env("BLUEFOG_FLEET_CONNECT_RETRIES") or 3),
        connect_backoff_s=float(_env("BLUEFOG_FLEET_CONNECT_BACKOFF")
                                or 1.0),
        connect_timeout_s=float(timeout) if timeout else None,
    )


def _initialize(spec: FleetSpec) -> None:
    """The one real call (tests monkeypatch this seam to drive the
    guard paths without a live coordinator)."""
    import jax
    kwargs = {}
    if spec.network_interface and spec.process_id == 0:
        # Pin the coordinator's LISTENING socket to the chosen NIC
        # (bfrun --network-interface; reference run.py:84-118 pins
        # NCCL/gloo ifaces the same way).  Resolved here, on the
        # coordinator's own machine — the launcher cannot know a remote
        # host's addresses.
        from ..run.network_util import interface_address
        port = spec.coordinator.rsplit(":", 1)[1]
        kwargs["coordinator_bind_address"] = (
            f"{interface_address(spec.network_interface)}:{port}")
    if spec.connect_timeout_s is not None:
        kwargs["initialization_timeout"] = spec.connect_timeout_s
    jax.distributed.initialize(
        coordinator_address=spec.coordinator,
        num_processes=spec.num_processes,
        process_id=spec.process_id, **kwargs)


def _benign(err: RuntimeError) -> bool:
    """Only "already initialized / called too late" is benign (the user
    or a previous bf.init did it).  A coordinator connection failure
    must NOT be swallowed — proceeding would silently train each host
    independently."""
    msg = str(err).lower()
    # covers "distributed.initialize should only be called once." and
    # older "already initialized" / ordering phrasings
    return ("only be called once" in msg or "already" in msg
            or "must be called before" in msg)


def _retryable(err: Exception) -> bool:
    """Coordinator-unreachable shapes worth another dial: connection
    refusals/timeouts surface as RuntimeError/ConnectionError with
    transport wording, depending on the jaxlib build."""
    if isinstance(err, (ConnectionError, TimeoutError, OSError)):
        return True
    msg = str(err).lower()
    return any(tok in msg for tok in (
        "unavailable", "deadline", "timed out", "timeout",
        "connection refused", "failed to connect", "unreachable"))


def ensure_initialized(fleet=None) -> dict:
    """Idempotent fleet bring-up; returns the structured diagnosis.

    ``status`` values: ``"ok"`` (this call joined the job),
    ``"noop"`` (no coordinator configured, or a previous call already
    joined), ``"adopted"`` (the runtime was initialized by someone
    else — the benign-RuntimeError branch, logged as a warning).  On a
    coordinator that never answers, raises :class:`FleetBootstrapError`
    after ``connect_retries`` dials with doubling backoff — the
    diagnosis rides the exception AND :func:`last_diagnosis`."""
    global _started, _last_diagnosis
    if _started:
        return {"kind": "fleet_bootstrap", "status": "noop",
                "reason": "already started in this process"}
    spec = resolve_fleet_spec(fleet)
    if spec is None or not spec.coordinator:
        return {"kind": "fleet_bootstrap", "status": "noop",
                "reason": "no coordinator configured"}
    diagnosis = {
        "kind": "fleet_bootstrap",
        "coordinator": spec.coordinator,
        "num_processes": int(spec.num_processes),
        "process_id": int(spec.process_id),
        "attempts": 0,
    }
    last_err: Optional[Exception] = None
    for attempt in range(1, max(1, int(spec.connect_retries)) + 1):
        diagnosis["attempts"] = attempt
        try:
            _initialize(spec)
        except RuntimeError as e:
            if _benign(e):
                logger.warning("jax.distributed.initialize skipped: %s", e)
                _started = True
                diagnosis.update(status="adopted", reason=str(e))
                _last_diagnosis = diagnosis
                return diagnosis
            if not _retryable(e):
                diagnosis.update(status="error", reason=str(e))
                _last_diagnosis = diagnosis
                raise
            last_err = e
        except Exception as e:
            if not _retryable(e):
                diagnosis.update(status="error", reason=str(e))
                _last_diagnosis = diagnosis
                raise
            last_err = e
        else:
            _started = True
            diagnosis.update(status="ok")
            _last_diagnosis = diagnosis
            return diagnosis
        if attempt < spec.connect_retries:
            delay = spec.connect_backoff_s * (2 ** (attempt - 1))
            logger.warning(
                "fleet bootstrap: coordinator %s unreachable "
                "(attempt %d/%d): %s — retrying in %.1fs",
                spec.coordinator, attempt, spec.connect_retries,
                last_err, delay)
            time.sleep(delay)
    # degrade loudly: a structured record, not a bare traceback
    diagnosis.update(status="unreachable", reason=str(last_err))
    _last_diagnosis = diagnosis
    logger.error("fleet bootstrap failed: %s", json.dumps(diagnosis))
    raise FleetBootstrapError(diagnosis)


def started() -> bool:
    """Whether this process already ran the bring-up (or adopted an
    externally-initialized runtime)."""
    return _started


def last_diagnosis() -> Optional[dict]:
    """The newest bring-up diagnosis record (None before any dial)."""
    return _last_diagnosis


def reset_for_testing() -> None:
    """Clear the module guard — test isolation only; resetting a live
    process does NOT tear down the jax.distributed runtime."""
    global _started, _last_diagnosis
    _started = False
    _last_diagnosis = None
