"""``bfrun --fleet N`` — the local fleet supervisor.

The reference launcher execve's ``mpirun`` and forgets its children;
this supervisor OWNS them.  It spawns N worker OS processes with
per-process env (fleet rank, peer map, per-rank metrics prefix), hears
their UDP heartbeats directly, reaps deaths via ``waitpid``
(``Popen.poll``), and drives the PR 13 elastic-membership protocol from
REAL process lifecycle:

* a worker that dies gets its ``rank_leave`` injected from an
  actually-dead process (``ElasticMembership.leave`` on the reaped
  exit, failure-as-departure);
* with ``--respawn`` a replacement is launched and re-admits through
  the full announce → sync → activate path — ``announce`` at spawn,
  ``mark_synced`` when the worker's bootstrap sends the *synced*
  datagram, activation when :meth:`ElasticMembership.observe_direct`
  sees its heartbeats fresh again;
* SIGTERM/SIGINT fan out to every child (grace period, then SIGKILL),
  and exit codes aggregate: per rank the LAST incarnation's code wins
  (a crashed rank whose respawn finished clean counts as recovered),
  the fleet's code is the first nonzero by rank order.

Every lifecycle action is banked as a ``fleet_event`` line in the
:class:`~bluefog_tpu.observability.export.FleetTrail` that ``bfmonitor
--fleet`` renders (docs/running.md "Fleet mode").
"""

import os
import signal
import socket
import struct
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import export as _export
from ..resilience.membership import (STATE_LEFT, ElasticMembership,
                                     LivenessConfig)
from . import peers as _peers

__all__ = ["SUPERVISOR_ENV", "RESPAWN_COUNT_ENV", "HB_HEARTBEAT",
           "HB_SYNCED", "send_heartbeat", "send_synced", "free_ports",
           "FleetSupervisor", "run_fleet"]

SUPERVISOR_ENV = "BLUEFOG_FLEET_SUPERVISOR"
RESPAWN_COUNT_ENV = "BLUEFOG_FLEET_RESPAWN_COUNT"

# heartbeat datagram: magic, kind, rank, step, pid
_HB = struct.Struct("<IIIII")
_HB_MAGIC = 0xB1F0FB
HB_HEARTBEAT = 0
HB_SYNCED = 1

_hb_sock: Optional[socket.socket] = None


def _heartbeat_addr() -> Optional[Tuple[str, int]]:
    text = os.environ.get(SUPERVISOR_ENV)
    if not text:
        return None
    host, port = text.rsplit(":", 1)
    return (host, int(port))


def send_heartbeat(step: int, *, rank: Optional[int] = None,
                   kind: int = HB_HEARTBEAT) -> bool:
    """Best-effort heartbeat datagram to the supervisor named by
    ``BLUEFOG_FLEET_SUPERVISOR`` (no-op outside a fleet).  Returns
    whether a datagram went out."""
    global _hb_sock
    addr = _heartbeat_addr()
    if addr is None:
        return False
    if rank is None:
        rank = int(os.environ.get(_peers.RANK_ENV, "0"))
    if _hb_sock is None:
        _hb_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        _hb_sock.sendto(
            _HB.pack(_HB_MAGIC, int(kind), int(rank), int(step),
                     os.getpid() & 0xFFFFFFFF), addr)
        return True
    except OSError:
        return False


def send_synced(step: int, *, rank: Optional[int] = None) -> bool:
    """Report parameter-bootstrap completion (a respawned worker caught
    up) — the supervisor maps it to ``ElasticMembership.mark_synced``,
    the sync half of announce → sync → activate."""
    return send_heartbeat(step, rank=rank, kind=HB_SYNCED)


def free_ports(n: int, *, kind: int = socket.SOCK_DGRAM) -> List[int]:
    """``n`` distinct currently-free loopback ports.  Held open until
    all are allocated so the OS can't hand out duplicates."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, kind)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


class FleetSupervisor:
    """Spawn, watch, respawn, and reap a fleet of worker processes.

    ``env_for_rank(rank)`` supplies each worker's base environment
    (platform flags, metrics prefix); the supervisor layers the fleet
    family on top: ``BLUEFOG_FLEET_RANK`` / ``_SIZE`` / ``_PEERS`` /
    ``_SUPERVISOR`` / ``_RESPAWN_COUNT``."""

    def __init__(self, command: Sequence[str], size: int, *,
                 respawn: bool = False, max_respawns: int = 1,
                 trail_path: str = "fleet.jsonl",
                 env_for_rank: Optional[Callable[[int], dict]] = None,
                 cfg: Optional[LivenessConfig] = None,
                 grace_s: float = 10.0, poll_s: float = 0.05):
        self.command = list(command)
        self.size = int(size)
        self.respawn = bool(respawn)
        self.max_respawns = int(max_respawns)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self._env_for_rank = env_for_rank or (lambda r: dict(os.environ))
        self.peer_map = {r: ("127.0.0.1", p)
                         for r, p in enumerate(free_ports(self.size))}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.setblocking(False)
        self.addr = self._sock.getsockname()
        # laxer-than-default staleness thresholds: the supervisor's
        # clock spans OS processes whose effective step clocks are only
        # loosely aligned (each paces itself), so a couple of steps of
        # cross-process skew must not read as death
        self.membership = ElasticMembership(
            self.size, cfg=cfg or LivenessConfig(suspect_after=4,
                                                 confirm_after=8))
        self.trail = _export.FleetTrail(
            trail_path, size=self.size, respawn=self.respawn,
            max_respawns=self.max_respawns, command=self.command)
        self.procs: Dict[int, subprocess.Popen] = {}
        self.respawns = {r: 0 for r in range(self.size)}
        self.final_rc: Dict[int, int] = {}
        self.last_hb = np.zeros((self.size,), np.int64)
        self._hb_logged = np.full((self.size,), -1, np.int64)
        self._stop = False
        self._term_sent = 0.0

    # -- spawning ------------------------------------------------------------

    def _worker_env(self, rank: int) -> dict:
        env = self._env_for_rank(rank)
        env.update({
            _peers.RANK_ENV: str(rank),
            _peers.SIZE_ENV: str(self.size),
            _peers.PEERS_ENV: _peers.format_peer_map(self.peer_map),
            SUPERVISOR_ENV: f"{self.addr[0]}:{self.addr[1]}",
            RESPAWN_COUNT_ENV: str(self.respawns[rank]),
        })
        return env

    def spawn(self, rank: int, *, event: str = "spawn"
              ) -> subprocess.Popen:
        proc = subprocess.Popen(self.command,
                                env=self._worker_env(rank))
        self.procs[rank] = proc
        self.trail.write_event(event, rank=rank, pid=proc.pid,
                               respawns=self.respawns[rank])
        return proc

    # -- liveness ------------------------------------------------------------

    def _drain_heartbeats(self) -> None:
        while True:
            try:
                data, _ = self._sock.recvfrom(_HB.size + 16)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if len(data) != _HB.size:
                continue
            magic, kind, rank, step, pid = _HB.unpack(data)
            if magic != _HB_MAGIC or not 0 <= rank < self.size:
                continue
            self.last_hb[rank] = max(self.last_hb[rank], step)
            if (self.membership.state_of(rank) == STATE_LEFT
                    and self.procs.get(rank) is not None
                    and self.procs[rank].poll() is None):
                # the directory's joiner grace is measured in fleet
                # steps, so a replacement whose interpreter boot
                # outlasts it gets evicted before it ever speaks.  A
                # datagram from a rank whose child process is alive is
                # direct proof of life: re-announce it and let it walk
                # announce -> sync -> activate again.
                self._record(self.membership.announce(rank, step))
            if kind == HB_SYNCED:
                self.membership.mark_synced(rank)
                self.trail.write_event("synced", rank=rank, pid=pid,
                                       step=step)
            elif step > self._hb_logged[rank]:
                self._hb_logged[rank] = step
                self.trail.write_event("heartbeat", rank=rank, pid=pid,
                                       step=step)

    def _observe(self) -> None:
        clock = int(self.last_hb.max())
        for tr_step, rank, state in self.membership.observe_direct(
                self.last_hb, clock):
            self.trail.write_event("membership", rank=rank,
                                   step=tr_step, transition=state)

    def _record(self, transition) -> None:
        if transition is not None:
            tr_step, rank, state = transition
            self.trail.write_event("membership", rank=rank, step=tr_step,
                                   transition=state)

    # -- lifecycle -----------------------------------------------------------

    def _reap(self) -> None:
        clock = int(self.last_hb.max())
        for rank, proc in list(self.procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del self.procs[rank]
            self.final_rc[rank] = rc
            self.trail.write_event("exit", rank=rank, pid=proc.pid,
                                   rc=rc)
            if rc == 0 or self._stop:
                # orderly departure (clean finish, or our own fan-out)
                self._record(self.membership.leave(rank, clock))
                continue
            # an actually-dead process: rank_leave driven by waitpid
            self._record(self.membership.leave(rank, clock))
            if self.respawn and self.respawns[rank] < self.max_respawns:
                self.respawns[rank] += 1
                self.spawn(rank, event="respawn")
                # replacement re-enters through announce -> sync ->
                # activate; sync arrives as its HB_SYNCED datagram
                self._record(self.membership.announce(rank, clock))

    def terminate(self) -> None:
        """Orderly shutdown: SIGTERM fan-out now, SIGKILL stragglers
        after the grace period (driven by the run loop)."""
        self._stop = True
        if self._term_sent:
            return
        self._term_sent = time.monotonic()
        for rank, proc in self.procs.items():
            if proc.poll() is None:
                self.trail.write_event("terminate", rank=rank,
                                       pid=proc.pid)
                try:
                    proc.terminate()
                except OSError:
                    pass

    def _enforce_grace(self) -> None:
        if (not self._term_sent
                or time.monotonic() - self._term_sent < self.grace_s):
            return
        for rank, proc in self.procs.items():
            if proc.poll() is None:
                self.trail.write_event("kill", rank=rank, pid=proc.pid)
                try:
                    proc.kill()
                except OSError:
                    pass

    def aggregate_rc(self) -> int:
        """First nonzero LAST-incarnation exit code by rank order — a
        crashed rank whose respawned replacement finished clean counts
        as recovered."""
        for rank in range(self.size):
            rc = self.final_rc.get(rank, 0)
            if rc != 0:
                return rc
        return 0

    def run(self) -> int:
        prev_int = signal.signal(signal.SIGINT,
                                 lambda *_: self.terminate())
        prev_term = signal.signal(signal.SIGTERM,
                                  lambda *_: self.terminate())
        try:
            for rank in range(self.size):
                self.spawn(rank)
            while self.procs:
                self._drain_heartbeats()
                self._observe()
                self._reap()
                self._enforce_grace()
                if self.procs:
                    time.sleep(self.poll_s)
            self._drain_heartbeats()
            rc = self.aggregate_rc()
            self.trail.write_event("done", rc=rc)
            return rc
        finally:
            signal.signal(signal.SIGINT, prev_int)
            signal.signal(signal.SIGTERM, prev_term)
            self._sock.close()


def run_fleet(args, prog: str = "bfrun") -> int:
    """The ``bfrun --fleet N`` entry: build per-rank worker envs from
    the common bfrun flags (each worker gets its own FULL-size virtual
    device view — fleet workers run independent meshes and share state
    over the plane gossip, not a gang collective) and supervise."""
    from ..run.run import _apply_common_flags
    size = int(args.fleet)
    if size < 1:
        raise SystemExit(f"{prog}: --fleet needs at least 1 process")
    base_prefix = os.environ.get("BLUEFOG_METRICS")

    def env_for_rank(rank: int) -> dict:
        env = dict(os.environ)
        _apply_common_flags(args, env, args.num_proc or size)
        env["BLUEFOG_EXPECTED_SIZE"] = str(args.num_proc or size)
        if base_prefix:
            env["BLUEFOG_METRICS"] = f"{base_prefix}rank{rank}-"
        return env

    trail_path = (getattr(args, "fleet_trail", None)
                  or (f"{base_prefix}{_export.FLEET_SUFFIX}"
                      if base_prefix else _export.FLEET_SUFFIX))
    sup = FleetSupervisor(
        args.command, size,
        respawn=bool(getattr(args, "respawn", False)),
        max_respawns=int(getattr(args, "max_respawns", 1) or 1),
        trail_path=trail_path, env_for_rank=env_for_rank)
    if getattr(args, "verbose", False):
        print(f"{prog}: fleet of {size} -> {trail_path} "
              f"(heartbeats on {sup.addr[0]}:{sup.addr[1]})",
              file=sys.stderr)
    return sup.run()
