"""Multi-process fleet runtime: real OS processes behind ``bf.init``.

Everything below this package used to live inside ONE Python process on
a virtual mesh.  ``bluefog_tpu.fleet`` is the jump to a supervised
fleet of OS processes (the reference's coordinator + launcher layers,
PAPER.md layers 2 and 6, in SPMD-native form):

- :mod:`.bootstrap` — the single ``jax.distributed.initialize`` call
  site: ``bf.init(fleet=...)`` resolves ``BLUEFOG_FLEET_*`` env or a
  :class:`~bluefog_tpu.fleet.bootstrap.FleetSpec`, dials the
  coordinator with bounded retry/backoff, and degrades loudly with a
  structured diagnosis.
- :mod:`.peers` — per-process gossip transport: each process publishes
  its telemetry-plane row over loopback UDP and merges neighbors' with
  the plane's own newest-version-wins rule
  (:func:`~bluefog_tpu.observability.plane.host_merge`), yielding a
  local :class:`~bluefog_tpu.observability.plane.FleetViewLive` that
  per-process ``RequestRouter``\\ s consume via ``observe_plane`` — no
  shared filesystem.
- :mod:`.supervisor` — ``bfrun --fleet N``: spawns N workers with
  per-process env, hears heartbeats, reaps deaths via ``waitpid``,
  drives the elastic-membership announce→sync→activate protocol from
  REAL process lifecycle, respawns with ``--respawn``, fans out
  SIGTERM, aggregates exit codes, and writes the ``fleet.jsonl`` trail
  ``bfmonitor --fleet`` renders.
- :mod:`.worker` — the demo fleet worker ``make fleet-smoke`` runs:
  train steps + plane gossip + a local serving router per process.

See docs/running.md "Fleet mode".
"""

from .bootstrap import (FleetSpec, FleetBootstrapError,  # noqa: F401
                        resolve_fleet_spec, ensure_initialized,
                        last_diagnosis)
from .peers import PlanePeer, parse_peer_map, format_peer_map  # noqa: F401

__all__ = ["FleetSpec", "FleetBootstrapError", "resolve_fleet_spec",
           "ensure_initialized", "last_diagnosis", "PlanePeer",
           "parse_peer_map", "format_peer_map"]
