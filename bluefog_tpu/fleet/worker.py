"""The demo fleet worker (``python -m bluefog_tpu.fleet.worker``).

One OS process of the ``make fleet-smoke`` fleet: it trains (a jitted
step whose compile count is asserted — process death elsewhere must
never recompile a survivor), gossips its telemetry-plane row to its
peers over :class:`~bluefog_tpu.fleet.peers.PlanePeer`, runs the FULL
serving tier locally with a :class:`RequestRouter` whose liveness comes
from the local gossiped view (``observe_plane`` — no shared
filesystem), heartbeats the supervisor, and banks a per-incarnation
result JSON the smoke harness asserts on.

A respawned incarnation (``BLUEFOG_FLEET_RESPAWN_COUNT > 0``) first
listens for the surviving fleet's gossip, fast-forwards its plane clock
past its dead incarnation's versions (:meth:`PlanePeer.resume_clock`),
and reports bootstrap completion with the *synced* datagram — the sync
half of the supervisor's announce → sync → activate re-admission.
"""

import argparse
import json
import os
import signal
import sys
import time

from . import peers as _peers
from . import supervisor as _sup

__all__ = ["main"]


def parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="bluefog-fleet-worker",
        description="demo worker for bfrun --fleet / make fleet-smoke")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--step-ms", type=float, default=40.0,
                    help="wall-clock pacing per step (keeps the fleet's "
                         "plane clocks roughly aligned)")
    ap.add_argument("--out", default=".",
                    help="directory for the per-incarnation result JSON")
    ap.add_argument("--sync-steps", type=int, default=3,
                    help="respawned incarnation: steps of fresh gossip "
                         "to fold before reporting synced")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    rank = int(os.environ.get(_peers.RANK_ENV, "0"))
    size = int(os.environ.get(_peers.SIZE_ENV, "1"))
    respawns = int(os.environ.get(_sup.RESPAWN_COUNT_ENV, "0"))

    import jax
    import jax.numpy as jnp
    import bluefog_tpu as bf
    from ..observability import plane as P
    from ..resilience import LivenessConfig
    from ..serving import (NoReplicaAvailable, ReplicaDeadError,
                           RequestRouter, ReplicaSet, StaleReplicaError,
                           WeightPublisher)

    bf.init()
    n = bf.size()

    stop = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *_: stop.update(flag=True))

    peer = _peers.PlanePeer(rank, size)
    readmitted = False

    @jax.jit
    def train_step(x, t):
        mixed = 0.5 * (x + jnp.roll(x, 1, axis=0))
        return mixed + 0.001 * jnp.sin(t)

    import numpy as np
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 8)), jnp.float32)

    params = {"w": jnp.asarray(rng.normal(size=(n, 4, 3)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)}
    # publisher/replica roles must be disjoint (serving_topology)
    if n >= 4:
        pubs, reps = [0, 1], [n - 2, n - 1]
    else:
        pubs, reps = [0], [n - 1]
    pub = WeightPublisher(params, pubs, reps)
    rs = ReplicaSet(pub, lambda p, b: b @ p["w"] + p["b"],
                    max_staleness=64)
    liveness = LivenessConfig(suspect_after=2, confirm_after=4)
    router = RequestRouter(rs, prefix=os.environ.get("BLUEFOG_METRICS"),
                           liveness=liveness)
    batch = jnp.ones((1, 4), jnp.float32)

    # pay the one compile BEFORE resuming the plane clock: everything
    # between resume_clock and the first publish is wall time the
    # surviving fleet keeps stepping through, and an effective clock
    # that starts a compile's worth of steps behind the fleet stays
    # behind it forever (the supervisor's staleness machine would keep
    # evicting the replacement as a stale joiner)
    train_step(x, jnp.float32(0)).block_until_ready()

    if respawns > 0:
        # listen for the survivors before speaking: resume_clock needs
        # the fleet's circulating versions (including the dead
        # incarnation's frozen row) in the table
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not stop["flag"]:
            peer.poll(0)
            if any(v > 0 for i, v in enumerate(peer.versions())
                   if i != rank):
                break
            time.sleep(0.02)
        peer.resume_clock(0)

    ok = failed = steps_done = 0
    served_by = {}
    seen_alive = set()
    dead_seen = set()

    for step in range(args.steps):
        if stop["flag"]:
            break
        x = train_step(x, jnp.float32(step))
        if respawns > 0:
            # keep the resumed clock glued to the fleet's: bring-up
            # stalls after resume_clock would otherwise leave this
            # incarnation permanently behind the supervisor's clock
            peer.chase_clock(step)
        eff = peer.eff_step(step)
        peer.publish(P.pack_payload(eff, staleness=0.0), step)
        view = peer.view()

        mask = view.alive_mask(liveness.suspect_after)
        for r in range(size):
            if r == rank:
                continue
            if mask[r] > 0:
                seen_alive.add(r)
            elif r in seen_alive:
                dead_seen.add(r)

        pub.publish(params, eff)
        rs.refresh(eff)
        router.observe_plane(view, step=eff)
        try:
            _, replica = router.route(batch, eff)
            ok += 1
            served_by[replica] = served_by.get(replica, 0) + 1
        except (NoReplicaAvailable, ReplicaDeadError,
                StaleReplicaError):
            failed += 1

        if respawns > 0 and len(seen_alive) >= min(2, size - 1):
            # re-send on a cadence, not once: an early synced datagram
            # can land mid-flap (the directory evicted this incarnation
            # again before its clock caught up) and eviction clears the
            # directory's synced bit
            if not readmitted or steps_done % 8 == 0:
                _sup.send_synced(eff, rank=rank)
                readmitted = True
        _sup.send_heartbeat(eff, rank=rank)
        steps_done += 1
        time.sleep(args.step_ms / 1000.0)

    result = {
        "rank": rank, "pid": os.getpid(), "respawn_count": respawns,
        "steps_done": steps_done,
        "compiles": int(train_step._cache_size()),
        "requests_ok": ok, "requests_failed": failed,
        "served_by": {str(k): v for k, v in served_by.items()},
        "failovers": [e.asdict() for e in router.failovers],
        "dead_seen": sorted(dead_seen),
        "readmitted": bool(readmitted),
        "eff_base": int(peer._base),
        "stopped_early": bool(stop["flag"]),
    }
    os.makedirs(args.out, exist_ok=True)
    out_path = os.path.join(args.out,
                            f"rank{rank}-run{respawns}.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    peer.close()
    bf.win_free()
    return 0


if __name__ == "__main__":
    sys.exit(main())
