"""Host-side cadence control for the asynchronous optimizer family.

Asynchrony in this package is DATA, not control flow: every rank's tick
runs the same compiled programs, and which ranks actually fire is a
host-built mask the :class:`CadenceScheduler` derives from per-rank
*periods* — rank ``i`` with period ``k_i`` fires on ticks where
``t % k_i == k_i - 1`` (the same convention as the sync wrappers'
``num_steps_per_communication``).  Period 1 everywhere IS the
synchronous optimizer, bit for bit.

The scheduler closes the loop with the health engine
(``observability/health.py``): a ``straggler`` verdict carries
``value = median_step / fleet_median`` — exactly the slowdown ratio —
so :meth:`CadenceScheduler.observe` throttles that rank to
``period = ceil(ratio)``, letting it adapt/gossip less often while the
fast ranks keep stepping.  The throttle is bounded: a period beyond
``BLUEFOG_ASYNC_MAX_STALENESS`` is REFUSED (clamped, counted in
``bf_async_refusals_total``) because the staleness a period-``k`` rank
imposes on its out-neighbors' buffers is exactly ``k`` folds
(docs/async.md "Staleness bound").
"""

import os
from typing import Dict, Optional

import numpy as np

from ..observability import metrics as _metrics

__all__ = ["CadenceScheduler", "resolve_periods", "resolve_max_staleness",
           "MAX_STALENESS_ENV", "PERIODS_ENV"]

MAX_STALENESS_ENV = "BLUEFOG_ASYNC_MAX_STALENESS"
PERIODS_ENV = "BLUEFOG_ASYNC_PERIODS"
DEFAULT_MAX_STALENESS = 8


def resolve_max_staleness(max_staleness: Optional[int] = None) -> int:
    """Explicit argument wins, else ``BLUEFOG_ASYNC_MAX_STALENESS``
    (default 8 — the worst un-folded delivery count any rank may impose
    on a neighbor's buffers)."""
    if max_staleness is not None:
        return int(max_staleness)
    return int(os.environ.get(MAX_STALENESS_ENV,
                              str(DEFAULT_MAX_STALENESS)))


def resolve_periods(size: int, periods=None) -> np.ndarray:
    """[N] int64 period vector: explicit argument wins, else
    ``BLUEFOG_ASYNC_PERIODS`` (comma list — one entry per rank, or a
    single value broadcast to the fleet), else all ones (synchronous
    cadence)."""
    if periods is None:
        raw = os.environ.get(PERIODS_ENV, "")
        if raw.strip():
            vals = [int(v) for v in raw.split(",") if v.strip()]
            periods = vals * size if len(vals) == 1 else vals
    if periods is None:
        return np.ones(size, dtype=np.int64)
    arr = np.asarray(periods, dtype=np.int64).reshape(-1)
    if arr.shape[0] != size:
        raise ValueError(
            f"periods has {arr.shape[0]} entries for a fleet of {size}")
    if (arr < 1).any():
        raise ValueError(f"periods must be >= 1, got {arr.tolist()}")
    return arr


class CadenceScheduler:
    """Per-rank step cadence with bounded-staleness refusal.

    ``periods[i] == k`` makes rank ``i`` fire (adapt + gossip) every
    ``k``-th tick; between fires its window buffers keep accumulating
    neighbor pushes (bounded staleness, ``ops/windows.py`` versions are
    the observable).  All methods are host-side numpy — the masks they
    produce flow into the compiled window programs as traced data, so
    period changes NEVER recompile (asserted in
    tests/test_async_train.py).
    """

    def __init__(self, size: int, periods=None, base_period: int = 1,
                 max_staleness: Optional[int] = None):
        self.size = int(size)
        self.base_period = int(base_period)
        self.max_staleness = resolve_max_staleness(max_staleness)
        self.periods = resolve_periods(self.size, periods)
        self.refusals = 0
        # ranks THIS scheduler throttled (observe()): only these are
        # restored to base_period when their straggler verdict clears —
        # user-pinned heterogeneous cadences stay untouched
        self._throttled = set()

    # -- mask production ------------------------------------------------------

    def active(self, step: int) -> np.ndarray:
        """[N] bool: which ranks fire at tick ``step`` (the
        ``t % k == k - 1`` convention of the sync wrappers'
        ``_should_communicate``)."""
        return (int(step) % self.periods) == (self.periods - 1)

    def staleness_bound(self) -> int:
        """Worst-case un-folded deliveries any buffer can accumulate:
        the largest period in the fleet."""
        return int(self.periods.max())

    # -- period control -------------------------------------------------------

    def set_period(self, rank: int, period: int) -> int:
        """Set rank's period, refusing past the staleness cap: a request
        beyond ``max_staleness`` is counted (``bf_async_refusals_total``)
        and CLAMPED to the cap — the rank is throttled as far as the
        bound allows, never further.  Returns the period applied."""
        if not 0 <= rank < self.size:
            raise ValueError(f"rank {rank} outside [0, {self.size})")
        period = int(period)
        if period < 1:
            raise ValueError(f"period must be >= 1, got {period}")
        if period > self.max_staleness:
            self.refusals += 1
            if _metrics.enabled():
                _metrics.counter(
                    "bf_async_refusals_total",
                    "cadence periods refused by the bounded-staleness "
                    "cap (BLUEFOG_ASYNC_MAX_STALENESS)").inc()
            period = self.max_staleness
        self.periods[rank] = period
        if _metrics.enabled():
            _metrics.gauge("bf_async_period",
                           "per-rank cadence period (ticks between "
                           "fires)").set(float(period), rank=str(rank))
        return period

    def observe(self, report) -> Dict[int, int]:
        """Consume a health report (``health.evaluate`` output): every
        ``straggler`` verdict's slowdown ratio (``value``) becomes that
        rank's period; ranks this scheduler throttled earlier whose
        verdicts cleared return to ``base_period``.  Returns the
        ``{rank: period}`` changes applied."""
        changes = {}
        flagged = set()
        for v in report.by_rule("straggler"):
            rank = getattr(v, "rank", None)
            if rank is None:
                continue
            flagged.add(rank)
            want = max(self.base_period,
                       int(np.ceil(float(v.value))))
            if want != int(self.periods[rank]):
                changes[rank] = self.set_period(rank, want)
            self._throttled.add(rank)
        for rank in sorted(self._throttled - flagged):
            self._throttled.discard(rank)
            if int(self.periods[rank]) != self.base_period:
                changes[rank] = self.set_period(rank, self.base_period)
        return changes

    # -- checkpointing --------------------------------------------------------

    def state_dict(self) -> dict:
        """JSON-able snapshot (``checkpoint.fleet_state_dict``'s
        ``async_cadence`` meta section): enough to resume mid-asynchrony
        with the same masks from the same tick."""
        return {"size": self.size, "base_period": self.base_period,
                "max_staleness": self.max_staleness,
                "periods": [int(p) for p in self.periods],
                "refusals": int(self.refusals),
                "throttled": sorted(int(r) for r in self._throttled)}

    def load_state_dict(self, state: dict) -> None:
        if int(state["size"]) != self.size:
            raise ValueError(
                f"cadence snapshot is for fleet size {state['size']}, "
                f"scheduler has {self.size}")
        self.base_period = int(state["base_period"])
        self.max_staleness = int(state["max_staleness"])
        self.periods = np.asarray(state["periods"], np.int64).reshape(-1)
        self.refusals = int(state.get("refusals", 0))
        self._throttled = set(int(r) for r in state.get("throttled", ()))
