"""Asynchronous optimizer family: per-rank cadence, no step barrier.

The sync window optimizers (``optim/wrappers.py`` win-put / push-sum)
advance every rank in lockstep.  Here each rank steps at its OWN period
(:class:`~.cadence.CadenceScheduler`): a tick where rank ``i`` is
inactive leaves its parameters, optimizer state, window tensor, and
push row untouched while its in-neighbor buffers keep ACCUMULATING
deliveries — bounded staleness, observable as the window version
counters (``ops.windows.win_version_vector``).  All of that asynchrony
is expressed as host-built numpy mask/weight matrices flowing into the
window kernels and ONE jitted masked-adapt program as traced data — so
cadence changes, straggler throttles, fault flips, and elastic joins
never recompile (compile-count asserted in tests/test_async_train.py).

Push-sum keeps the average unbiased under this asymmetric staleness:
the window holds the biased iterate ``x`` with the associated-P scalar
riding EVERY op at identical weights (``_push_fn`` / ``_update_fn``),
so the conservation invariant

    (sum_i x_i + undelivered buffer mass)
    / (sum_i P_i + buffered P)  ==  mean(x_init)

holds exactly at every tick whatever the cadences do —
:func:`conserved_debiased_mean` is the assertable form
(``make async-smoke`` checks it each step).  Period 1 everywhere
reproduces the synchronous optimizers bit for bit; see docs/async.md
for the cadence model, the staleness bound, and the de-bias math.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import timeline as _tl
from ..compress import compressors as _cp
from ..context import ctx
from ..observability import ingraph as IG
from ..observability import metrics as _metrics
from ..observability import phases as _ph
from ..ops import api as _api
from ..ops import fusion as _fusion
from ..ops import windows as W
from ..optim import strategies as S
from ..optim._plumbing import mesh_plumbing, step_cache_key
from ..utils.compile_cache import note_step_cache
from .cadence import CadenceScheduler

__all__ = ["win_put_step", "push_sum_step", "AsyncWinPutOptimizer",
           "AsyncPushSumOptimizer", "conserved_debiased_mean"]

# bflint knob-outside-cache-key: per-INSTANCE constants.  The step cache
# lives on the optimizer instance, so knobs fixed in __init__ for the
# instance's lifetime are keyed by instance identity; ``window_prefix``
# names the window (identity, not program shape); ``periods`` /
# ``scheduler`` produce the per-tick masks — traced DATA by design (the
# whole point of this package is that cadence never recompiles); and
# ``trail`` is a host-side JSONL sink.
_STEP_KEY_EXEMPT_KNOBS = frozenset({
    "window_prefix", "periods", "scheduler", "trail",
})


def conserved_debiased_mean(name: str):
    """The push-sum conservation observable, host-side: per-element
    ``(sum_ranks tensor + undelivered buffer mass) / (sum P + buffered
    P)`` over one window's state snapshot — EXACTLY the initial
    parameter mean at every tick of a clean (no-death) async run,
    whatever the cadences (mass in flight is still mass).  The per-step
    unbiasedness assertion of ``make async-smoke`` and the async tests.
    Call it between steps (no nonblocking op staged).  Returns the
    window's creation tree with the rank axis dropped."""
    w = W._window(name)
    n = w.topo.size
    denom = float(np.asarray(w.p).sum() + np.asarray(w.p_buffers).sum())

    def leaf_mass(t, b):
        # t: [N, *shape]; b: [N, slots, *shape] (padded slots are zero;
        # fused windows carry one flat leaf — the math is shape-blind)
        t = np.asarray(t)
        b = np.asarray(b)
        return (t.sum(axis=0) + b.sum(axis=(0, 1))) / denom

    mean = jax.tree.map(leaf_mass, w.tensor, w.buffers)
    # broadcast back to the global view and unpack to the creation tree
    ext = w.external(jax.tree.map(
        lambda m: jnp.broadcast_to(jnp.asarray(m), (n,) + m.shape), mean))
    return jax.tree.map(lambda a: np.asarray(a[0]), ext)


class _AsyncWindowBase:
    """Shared machinery for the async win-put / push-sum wrappers: one
    window for the whole parameter pytree (like the sync
    ``_WindowOptimizerBase``), a :class:`CadenceScheduler` producing the
    per-tick active masks, and ONE jitted masked-adapt program —
    inactive ranks pass their params and optimizer state through a
    ``jnp.where`` select inside the same compiled step, so a cadence
    flip is a different mask value, never a different program."""

    _instance_counter = [0]   # default names stay unique AND deterministic

    def __init__(self, base, window_prefix: Optional[str] = None,
                 periods=None, scheduler: Optional[CadenceScheduler] = None,
                 telemetry: Optional[bool] = None, compression=None,
                 trail=None):
        self.base = base
        if window_prefix is None:
            window_prefix = f"async_opt{self._instance_counter[0]}"
            self._instance_counter[0] += 1
        self._name = window_prefix + ".params"
        self._created = False
        self.telemetry = telemetry
        # wire compression rides win_create (the window owns the wire
        # format), exactly like the sync window family
        self.compression = _cp.resolve_compression(compression)
        self.trail = trail
        if scheduler is None:
            scheduler = CadenceScheduler(ctx().size, periods=periods)
        elif periods is not None:
            raise ValueError("pass periods= or scheduler=, not both")
        self.scheduler = scheduler
        self._step_cache = {}

    @property
    def periods(self) -> np.ndarray:
        return self.scheduler.periods

    @property
    def window_name(self) -> str:
        return self._name

    def _require_init(self):
        if not self._created:
            raise RuntimeError(
                "async optimizer used before init(); call "
                "state = opt.init(params) first to create the windows")

    def init(self, params, zero_init: bool = False):
        if not W.win_create(params, self._name, zero_init=zero_init,
                            compression=self.compression):
            raise ValueError(f"Cannot allocate window for {self._name}")
        self._created = True
        cx = ctx()
        A = (cx.compiled_topology.weight_matrix != 0).astype(np.float64)
        np.fill_diagonal(A, 0.0)
        self._adj = A
        return jax.vmap(self.base.init)(params)

    def free(self):
        if self._name in W.get_current_created_window_names():
            W.win_free(self._name)
        self._created = False

    def _alive_vec(self, alive) -> np.ndarray:
        n = self.scheduler.size
        if alive is None:
            return np.ones(n)
        return np.asarray(alive, np.float64).reshape(-1)

    def _exec_config(self, params):
        """The step-cache key — same tuple home as the sync wrappers
        (``optim/_plumbing.step_cache_key``), so whatever invalidates a
        sync step invalidates an async one.  Cadence, liveness, and
        straggler throttles are deliberately ABSENT: they are traced
        data."""
        cx = ctx()
        fuse = _fusion.fusion_enabled(None)
        bucket = _fusion.resolve_max_bucket_bytes(None)
        telemetry = IG.telemetry_enabled(self.telemetry)
        key = step_cache_key(cx, params, _api._nar_backend(), fuse, bucket,
                             False, telemetry, self.compression,
                             gossip_axis=cx.rank_axis)
        return telemetry, key

    def _build(self, telemetry: bool):
        """One jitted masked local-adapt program: ``adapt_in`` is the
        tree active ranks adapt (post-fold average / biased iterate),
        ``keep`` the rows inactive ranks keep verbatim.  The optimizer
        state is donated on TPU (same guard as the window kernels —
        donation on host platforms only warns)."""
        cx = ctx()
        pl = mesh_plumbing(cx, False)
        core = S.local_sgd_like_step(self.base, telemetry=telemetry,
                                     axis_name=cx.rank_axis)

        def stepper(keep, adapt_in, grads, opt_state, step_idx, active):
            def shard_fn(pk, pa, g, st, si, act):
                gate = pl.unwrap(act) != 0
                sel = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(gate, n, o), new, old)
                out = core(pl.unwrap(pa), pl.unwrap(g), pl.unwrap(st), si)
                if telemetry:
                    p_new, st_new, snap = out
                else:
                    p_new, st_new = out
                p_out = sel(p_new, pl.unwrap(pk))
                st_out = sel(st_new, pl.unwrap(st))
                if telemetry:
                    return (pl.rewrap(p_out), pl.rewrap(st_out),
                            pl.rewrap(snap))
                return pl.rewrap(p_out), pl.rewrap(st_out)

            n_out = 3 if telemetry else 2
            out = jax.shard_map(
                shard_fn, mesh=pl.mesh,
                in_specs=(pl.spec, pl.spec, pl.spec, pl.spec, P(),
                          pl.spec),
                out_specs=(pl.spec,) * n_out,
                check_vma=not _api._nar_backend().startswith("pallas"),
            )(pl.reshape_in(keep), pl.reshape_in(adapt_in),
              pl.reshape_in(grads), pl.reshape_in(opt_state), step_idx,
              pl.reshape_in(active))
            return tuple(pl.reshape_out(o) for o in out)

        donate = (3,) if jax.default_backend() == "tpu" else ()
        return jax.jit(stepper, donate_argnums=donate)

    def _masked_adapt(self, keep, adapt_in, grads, opt_state, step,
                      active):
        telemetry, key = self._exec_config(keep)
        hit = key in self._step_cache
        note_step_cache(hit)
        if not hit:
            self._step_cache[key] = self._build(telemetry)
        act = jnp.asarray(np.asarray(active, np.int32))
        with _ph.step_phase("compute"):
            return self._step_cache[key](keep, adapt_in, grads, opt_state,
                                         jnp.asarray(step, jnp.int32), act)

    def _observe_staleness(self):
        """Pre-fold effective-staleness vector, only when someone is
        listening (one device sync)."""
        if _metrics.enabled() or self.trail is not None:
            return W.win_version_vector(self._name)
        return None

    def _note(self, step, active, stale, p=None):
        """Metrics + trail after the fold.  ``stale`` is the PRE-fold
        version vector: for firing ranks it is exactly the deliveries
        the fold just consumed."""
        sched = self.scheduler
        fired = np.flatnonzero(active)
        stale_max = (float(np.max(stale[fired])) if stale is not None
                     and fired.size else 0.0)
        if _metrics.enabled():
            steps = _metrics.counter(
                "bf_async_steps_total",
                "asynchronous optimizer fires per rank")
            for r in fired:
                steps.inc(rank=str(int(r)))
            if stale is not None and fired.size:
                hist = _metrics.histogram(
                    "bf_async_staleness_steps",
                    "un-folded deliveries consumed per fold (effective "
                    "staleness)", buckets=(0, 1, 2, 4, 8, 16, 32))
                for r in fired:
                    hist.observe(float(stale[r]))
            if p is not None:
                _metrics.gauge(
                    "bf_async_p_drift",
                    "push-sum associated-P spread (max - min) across "
                    "the fleet").set(float(p.max() - p.min()))
            per = _metrics.gauge(
                "bf_async_period",
                "per-rank cadence period (ticks between fires)")
            for r in range(sched.size):
                per.set(float(sched.periods[r]), rank=str(r))
        if self.trail is not None:
            self.trail.write_step(
                int(step), active=int(len(fired)),
                staleness_max=stale_max,
                p_min=(float(p.min()) if p is not None else None),
                p_max=(float(p.max()) if p is not None else None),
                periods=sched.periods, refusals=sched.refusals)


class AsyncWinPutOptimizer(_AsyncWindowBase):
    """Asynchronous win-put flavor: active ranks put their params to
    live out-neighbors and fold their buffers with the averaging
    ``win_update``; inactive ranks neither push (their rows of the put
    matrix are zero — no delivery, no version bump) nor fold (their
    columns of the fold matrix are zero — ``_update_fn`` leaves
    zero-weight columns' buffers and versions untouched, so deliveries
    keep accumulating until their next fire).  A dead neighbor's
    buffer mass degrades to the self weight through the shared
    ``win_update(alive=)`` contract — the same staleness fold serving
    uses (docs/windows.md)."""

    def step(self, params, grads, opt_state, step: int = 0, alive=None):
        self._require_init()
        alive_v = self._alive_vec(alive)
        active = self.scheduler.active(step) & (alive_v > 0)
        stale = self._observe_staleness()
        fire = active.astype(np.float64)
        # rows: only firing sources put; columns: dead destinations get
        # nothing (their buffers would never be read)
        D = self._adj * fire[:, None] * (alive_v > 0)[None, :]
        tok = _tl.op_start_us()
        with _ph.step_phase("exchange"):
            W.win_wait(W.win_put_nonblocking(params, self._name,
                                             dst_weights=D))
        _tl.record_gossip_round(step, tok)
        with _ph.step_phase("fold"):
            sw, U = self._fold_weights(active)
            averaged = W.win_update(self._name, self_weight=sw,
                                    neighbor_weights=U, require_mutex=True,
                                    alive=alive_v)
        out = self._masked_adapt(params, averaged, grads, opt_state, step,
                                 active)
        self._note(step, active, stale)
        return out

    def _fold_weights(self, active):
        """Uniform ``1/(in_degree+1)`` averaging weights with inactive
        DESTINATIONS gated off (zero column + self weight 1 keeps their
        tensor, buffers, and versions untouched).  Dead-row handling is
        NOT here — it rides ``win_update(alive=)``, which moves a dead
        in-neighbor's weight onto the self weight (the shared
        serving/training staleness-fold contract)."""
        n = self._adj.shape[0]
        indeg = self._adj.sum(axis=0)
        col = 1.0 / (indeg + 1.0)
        U = self._adj * col[None, :]
        fire = active.astype(np.float64)
        U = U * fire[None, :]
        sw = np.where(active, col, 1.0)
        return sw, U


class AsyncPushSumOptimizer(_AsyncWindowBase):
    """Asynchronous gradient-push: the window holds the biased iterate
    ``x`` with the associated-P scalar riding every op; user-visible
    params are the de-biased ``x / P``.  Per tick: masked local adapt
    on the biased iterate, self-scaled push-accumulate from firing
    ranks (per-source ``alpha = 1/(live_out_degree+1)`` keeps each
    source's outgoing mass at exactly 1 even as deaths shrink its edge
    set), then a per-destination-gated SUM collect — firing ranks
    consume their accumulated buffers (``reset=True``), idle ranks'
    buffers keep growing.  Dead in-neighbor rows are DROPPED from the
    collect (``win_update_then_collect(alive=)`` semantics — a sum must
    not move lost mass to the self weight); P rides the identical
    weights, so the de-bias stays exact under the mask (the PR 11
    masked-weights invariant, extended to the training path)."""

    def init(self, params):
        W.turn_on_win_ops_with_associated_p()
        return super().init(params, zero_init=True)

    def _debias(self, tree):
        p = W.win_associated_p_vector(self._name)  # [N] device, no sync
        return jax.tree.map(
            lambda leaf: leaf / p.reshape(
                (-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype), tree)

    def _push_weights(self, active, alive_v):
        """(self_weight [N], dst_weights [N,N]) for this tick: firing
        sources push ``alpha_i`` to each LIVE out-neighbor and keep
        ``alpha_i`` (row sum exactly 1 — mass conservation); idle and
        dead sources have zero rows (no delivery, no version bump) and
        self weight 1 (tensor preserved)."""
        A = self._adj * (alive_v > 0)[None, :]
        outdeg = A.sum(axis=1)
        alpha = 1.0 / (outdeg + 1.0)
        fire = active.astype(np.float64)
        D = A * alpha[:, None] * fire[:, None]
        sw = np.where(active, alpha, 1.0)
        return sw, D

    def _collect_weights(self, active, alive_v):
        """SUM-collect weights: firing destinations take every live
        in-neighbor buffer at weight 1 (self weight 1, ``reset=True``
        zeroes exactly the slots read); idle destinations' columns are
        zero — ``_update_fn`` gates the reset/version-clear on
        ``weight != 0``, so their buffers keep accumulating.  Dead rows
        are pre-masked out (dropped, not self-shifted: sum semantics)."""
        fire = active.astype(np.float64)
        U = self._adj * (alive_v > 0)[:, None] * fire[None, :]
        sw = np.ones(self._adj.shape[0])
        return sw, U

    def step(self, params, grads, opt_state, step: int = 0, alive=None):
        self._require_init()
        alive_v = self._alive_vec(alive)
        active = self.scheduler.active(step) & (alive_v > 0)
        # the biased iterate lives in the window; `params` is the
        # de-biased view; gradients are taken at the de-biased point
        # (stochastic gradient-push), adapt applies to the biased one
        biased = W.win_fetch(self._name)
        out = self._masked_adapt(biased, biased, grads, opt_state, step,
                                 active)
        adapted, opt_state = out[0], out[1]
        stale = self._observe_staleness()
        sw, D = self._push_weights(active, alive_v)
        tok = _tl.op_start_us()
        with _ph.step_phase("exchange"):
            # win_accumulate publishes `adapted * sw` as the new window
            # tensor (idle rows: sw 1, value unchanged) and delivers the
            # weighted rows — one staged program, committed by win_wait
            W.win_wait(W.win_accumulate_nonblocking(
                adapted, self._name, self_weight=sw, dst_weights=D,
                require_mutex=True))
        _tl.record_gossip_round(step, tok)
        with _ph.step_phase("fold"):
            sw2, U = self._collect_weights(active, alive_v)
            collected = W.win_update(self._name, self_weight=sw2,
                                     neighbor_weights=U, reset=True,
                                     require_mutex=True)
        p = (np.asarray(W.win_associated_p_vector(self._name))
             if (_metrics.enabled() or self.trail is not None) else None)
        self._note(step, active, stale, p=p)
        result = self._debias(collected)
        if len(out) == 3:
            return result, opt_state, out[2]
        return result, opt_state

    def bootstrap_rank(self, rank: int, alive=None):
        """Admit an (elastic) joiner mid-asynchrony: one
        ``win_bootstrap_rank`` fold with ``reset=True`` — the pulled
        slots must not re-enter the next SUM collect as phantom mass —
        after which the joiner's ``x / P`` sits at the live de-biased
        average (``win_get`` moves P with the same weights; no extra
        plumbing).  Give the rank period 1 until its next health
        review."""
        self._require_init()
        out = W.win_bootstrap_rank(self._name, rank,
                                   alive=self._alive_vec(alive),
                                   reset=True)
        self.scheduler.set_period(rank, self.scheduler.base_period)
        return self._debias(out)


def win_put_step(base, window_prefix: Optional[str] = None, periods=None,
                 scheduler: Optional[CadenceScheduler] = None,
                 telemetry: Optional[bool] = None, compression=None,
                 trail=None) -> AsyncWinPutOptimizer:
    """Asynchronous win-put optimizer factory (the async mirror of
    ``DistributedWinPutOptimizer``): each rank fires at its own period
    (``periods`` [N] / ``scheduler`` / ``BLUEFOG_ASYNC_PERIODS``; all
    ones = the synchronous optimizer bit for bit).  ``step(params,
    grads, state, step=t, alive=mask)`` — see docs/async.md."""
    return AsyncWinPutOptimizer(base, window_prefix=window_prefix,
                                periods=periods, scheduler=scheduler,
                                telemetry=telemetry,
                                compression=compression, trail=trail)


def push_sum_step(base, window_prefix: Optional[str] = None, periods=None,
                  scheduler: Optional[CadenceScheduler] = None,
                  telemetry: Optional[bool] = None, compression=None,
                  trail=None) -> AsyncPushSumOptimizer:
    """Asynchronous push-sum optimizer factory (the async mirror of
    ``DistributedPushSumOptimizer``): unbiased average under per-rank
    cadences via the associated-P scalar.  ``step(params, grads, state,
    step=t, alive=mask)`` returns the de-biased view — see
    docs/async.md for the conservation invariant and staleness bound."""
    return AsyncPushSumOptimizer(base, window_prefix=window_prefix,
                                 periods=periods, scheduler=scheduler,
                                 telemetry=telemetry,
                                 compression=compression, trail=trail)
