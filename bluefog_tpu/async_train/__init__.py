"""Asynchronous training subsystem: push-sum / win-put gossip SGD with
no cross-rank step barrier.

Each rank steps at its own cadence (:class:`CadenceScheduler`);
neighbor state arrives through the nonblocking one-sided windows
(``ops/windows.py``), and push-sum's associated-P scalar keeps the
fleet average unbiased under asymmetric staleness.  See docs/async.md
for the cadence model, the staleness bound, the de-bias math, and the
composition table (compression, elastic membership, chaos fault plans,
durable checkpoints).
"""

from .cadence import (CadenceScheduler, resolve_max_staleness,
                      resolve_periods)
from .steps import (AsyncPushSumOptimizer, AsyncWinPutOptimizer,
                    conserved_debiased_mean, push_sum_step, win_put_step)

__all__ = [
    "CadenceScheduler", "resolve_periods", "resolve_max_staleness",
    "win_put_step", "push_sum_step",
    "AsyncWinPutOptimizer", "AsyncPushSumOptimizer",
    "conserved_debiased_mean",
]
