"""Replica half of the serving tier: bounded-staleness weight folds.

A :class:`ReplicaSet` drives the serving ranks' side of the parameter
window the :class:`~.publisher.WeightPublisher` feeds.  Each ``refresh``
is one ``win_update(alive=)`` fold: every replica row absorbs its
in-publisher buffers (weight ``1/in_degree`` each, self weight 0 — the
replica *tracks* the publisher average rather than mixing toward it),
while a dead publisher's row degrades to self weight via the liveness
mask, so a crashed trainer's frozen buffer never poisons the fold.

**Bounded staleness** is the tier's serving contract: per replica the
set tracks a *watermark* — the training step of the OLDEST live feed the
replica has actually folded (publisher version headers × window version
counters) — and ``staleness = now_step - watermark``.  A replica whose
staleness exceeds ``BLUEFOG_SERVE_MAX_STALENESS`` refuses to serve
(:class:`StaleReplicaError`), which is what lets the router promise
every answered request was computed on weights at most K steps old
(docs/serving.md "The staleness model").

``serve`` runs the caller's ``apply_fn`` on the replica's folded row —
a dead replica raises :class:`ReplicaDeadError` (the connection-refused
analog the router's failover path consumes).
"""

import math
import time
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from ..observability import metrics as _metrics
from ..ops import windows as _win
from .publisher import WeightPublisher, resolve_max_staleness

__all__ = ["ReplicaSet", "ReplicaDeadError", "StaleReplicaError"]


class ReplicaDeadError(RuntimeError):
    """Serving a dead replica rank — the connection-refused analog."""

    def __init__(self, rank: int):
        super().__init__(f"replica rank {rank} is down")
        self.rank = rank


class StaleReplicaError(RuntimeError):
    """A replica past the staleness bound refused to serve."""

    def __init__(self, rank: int, staleness: float, bound: int):
        super().__init__(
            f"replica rank {rank} is {staleness} steps stale "
            f"(bound {bound}); refusing to serve")
        self.rank = rank
        self.staleness = staleness
        self.bound = bound


class ReplicaSet:
    """The serving ranks over one publisher's parameter window.

    ``apply_fn(params_row, batch)`` is the inference function — it
    receives ONE replica's param tree (no leading mesh axis) and the
    request batch.  ``max_staleness`` defaults to
    ``BLUEFOG_SERVE_MAX_STALENESS`` (4 steps).
    """

    def __init__(self, publisher: WeightPublisher,
                 apply_fn: Callable, *,
                 max_staleness: Optional[int] = None):
        self.publisher = publisher
        self.apply_fn = apply_fn
        self.max_staleness = resolve_max_staleness(max_staleness)
        if self.max_staleness < 0:
            raise ValueError(
                f"max_staleness must be >= 0, got {self.max_staleness}")
        self.replicas: List[int] = list(publisher.replicas)
        # standby capacity replicas (pre-allocated in the window
        # topology): they FOLD like any replica — staying warm with
        # watermarks tracked — but serve nothing until admit() moves
        # them into the active set (docs/serving.md "Replica
        # autoscaling")
        self.standby: List[int] = list(getattr(publisher, "standby", ()))
        self.name = publisher.name
        n = publisher.topo.size
        tracked = self.replicas + self.standby
        # fold weights: in-publisher rows 1/in_degree, replica self 0 —
        # the masked fold moves a dead feed's mass back to self
        U = publisher.topo.weight_matrix.copy().astype(np.float64)
        np.fill_diagonal(U, 0.0)
        sw = np.ones((n,), np.float64)
        sw[tracked] = 0.0
        self._U, self._sw = U, sw
        self._in_pubs: Dict[int, List[int]] = {
            r: publisher.in_publishers(r) for r in tracked}
        # delivered[r][p]: the publisher-step of the newest put from p
        # that replica r has folded (None = never)
        self._delivered: Dict[int, Dict[int, Optional[int]]] = {
            r: {p: None for p in self._in_pubs[r]} for r in tracked}
        self._watermark: Dict[int, Optional[int]] = {
            r: None for r in tracked}
        self._fetched = None
        self.last_fold_s: Optional[float] = None

    # -- elastic admission (autoscaling hook) -------------------------------

    def admit(self, rank: int) -> bool:
        """Activate a pre-allocated standby replica (elastic
        scale-up).  Its window row, fold weights, and buffer slots have
        existed since ``win_create`` — admission is host bookkeeping on
        the same compiled programs, zero recompiles.  A standby that
        kept folding is warm (within the staleness bound immediately);
        a cold one stays unroutable until fresh folds land — the
        syncing half of the admission protocol.  Returns False when the
        rank is already active."""
        if rank in self.replicas:
            return False
        if rank not in self.standby:
            raise ValueError(
                f"rank {rank} is not a standby replica of window "
                f"{self.name!r} (standby: {self.standby}) — capacity "
                f"must be pre-allocated at WeightPublisher(standby=)")
        self.standby.remove(rank)
        self.replicas.append(rank)
        if _metrics.enabled():
            _metrics.counter(
                "bf_serve_admissions_total",
                "standby replicas admitted into the serving set").inc()
        return True

    def retire(self, rank: int) -> None:
        """Orderly scale-down: move an active replica back to standby.
        Its row keeps folding (warm for re-admission); it just stops
        being servable."""
        if rank not in self.replicas:
            raise ValueError(f"rank {rank} is not an active serving "
                             f"replica (replicas: {self.replicas})")
        if len(self.replicas) == 1:
            raise ValueError("cannot retire the last serving replica")
        self.replicas.remove(rank)
        self.standby.append(rank)
        if _metrics.enabled():
            _metrics.counter(
                "bf_serve_retirements_total",
                "replicas retired from the serving set back to standby"
            ).inc()

    # -- the fold -----------------------------------------------------------

    def refresh(self, step: int, alive=None) -> Dict[int, float]:
        """Fold pending publications into every replica row and advance
        the staleness watermarks; returns ``{replica: staleness}``.

        ``alive`` (optional [N] mask): dead PUBLISHERS degrade to
        self-weight in the fold (``win_update(alive=)``) and stop
        counting toward the watermark — a replica whose only live feeds
        go silent therefore ages out of the staleness bound instead of
        serving a frozen buffer as fresh.
        """
        alive_row = None if alive is None else np.asarray(
            alive, np.float64).reshape(-1)
        # promote any staged (un-waited) nonblocking puts: the fold must
        # see the newest completed publication
        _win.win_flush(self.name)
        tracked = self.replicas + self.standby
        fresh: Dict[int, List[int]] = {}
        for r in tracked:
            vers = _win.get_win_version(self.name, r)
            fresh[r] = [p for p in self._in_pubs[r] if vers.get(p, 0) > 0]
            for p in fresh[r]:
                self._delivered[r][p] = self.publisher.last_published.get(p)
        t0 = time.perf_counter()
        _win.win_update(self.name, self_weight=self._sw,
                        neighbor_weights=self._U, reset=False,
                        alive=alive_row)
        self.last_fold_s = time.perf_counter() - t0
        self._fetched = None
        for r in tracked:
            feeds = [p for p in self._in_pubs[r]
                     if alive_row is None or alive_row[p] > 0]
            if feeds:
                marks = [self._delivered[r][p] for p in feeds]
                if all(m is not None for m in marks):
                    # the OLDEST live feed bounds what the fold blended in
                    self._watermark[r] = min(marks)
        out = self.staleness(step)
        if _metrics.enabled():
            _metrics.histogram(
                "bf_serve_fold_seconds",
                "wall time of one replica-side win_update fold").observe(
                self.last_fold_s)
            g = _metrics.gauge(
                "bf_serve_staleness",
                "replica staleness in steps (now - watermark)")
            for r, s in out.items():
                g.set(s if math.isfinite(s) else -1.0, replica=r)
        return out

    # -- staleness ----------------------------------------------------------

    def staleness_of(self, rank: int, step: int) -> float:
        """Steps since ``rank``'s watermark (``inf`` before any fold)."""
        mark = self._watermark.get(rank)
        return math.inf if mark is None else float(int(step) - mark)

    def staleness(self, step: int) -> Dict[int, float]:
        return {r: self.staleness_of(r, step) for r in self.replicas}

    def can_serve(self, rank: int, step: int) -> bool:
        return self.staleness_of(rank, step) <= self.max_staleness

    # -- serving ------------------------------------------------------------

    def params_of(self, rank: int):
        """``rank``'s folded serving weights (one row of the window)."""
        if self._fetched is None:
            self._fetched = _win.win_fetch(self.name)
        return jax.tree.map(lambda a: a[rank], self._fetched)

    def serve(self, rank: int, batch, step: int, alive=None):
        """Answer one request on replica ``rank``.

        Raises :class:`ReplicaDeadError` when the rank is down (the
        router's failover trigger) and :class:`StaleReplicaError` when
        its staleness exceeds the bound — a replica never silently
        serves weights older than the contract.
        """
        if rank not in self.replicas:
            if rank in self.standby:
                raise ValueError(
                    f"rank {rank} is a standby replica not yet admitted "
                    f"(call ReplicaSet.admit / RequestRouter.admit first)")
            raise ValueError(f"rank {rank} is not a serving replica "
                             f"(replicas: {self.replicas})")
        if alive is not None and np.asarray(alive).reshape(-1)[rank] <= 0:
            raise ReplicaDeadError(rank)
        stale = self.staleness_of(rank, step)
        if stale > self.max_staleness:
            if _metrics.enabled():
                _metrics.counter(
                    "bf_serve_stale_refusals_total",
                    "requests a replica refused past the staleness bound"
                ).inc(replica=str(rank))
            raise StaleReplicaError(rank, stale, self.max_staleness)
        out = self.apply_fn(self.params_of(rank), batch)
        if _metrics.enabled():
            _metrics.counter(
                "bf_serve_requests_total",
                "inference requests answered, by replica").inc(
                replica=str(rank))
        return out

    def close(self) -> None:
        self.publisher.close()
