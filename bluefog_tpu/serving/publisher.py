"""Publisher half of the serving tier: training ranks ship weights.

A :class:`WeightPublisher` owns the dedicated *parameter window* — a
``win_create`` window over the model's global-view param tree, compiled on
its own publisher->replica graph (:func:`serving_topology`, riding
``win_create(topo=)``) so serving traffic never shares edges or buffer
slots with training gossip.  ``publish`` moves every publisher rank's
current weights into its replica destinations' window buffers in ONE
compressed nonblocking ``win_put`` — dense quantizers (``int8``/``fp8``)
are wire-legal on windows (docs/compression.md), so the parameter stream
rides the wire at a fraction of full precision while the replica-side
buffers stay exact-precision decodes.

The publisher also keeps the host-side *version header* of the stream:
``last_published[rank]`` is the training step each publisher rank most
recently shipped.  Replicas derive their bounded-staleness watermarks
from it plus the window's per-slot version counters (which tell a
replica WHETHER fresh data arrived; the header tells it from WHICH step)
— see ``serving/replica.py``.
"""

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compress import compressors as _compress
from ..context import ctx
from ..observability import metrics as _metrics
from ..ops import windows as _win
from ..parallel.schedule import CompiledTopology, compile_weight_matrix

__all__ = [
    "WeightPublisher", "serving_topology",
    "MAX_STALENESS_ENV", "PUBLISH_EVERY_ENV", "COMPRESS_ENV",
    "DEFAULT_WINDOW_NAME",
]

MAX_STALENESS_ENV = "BLUEFOG_SERVE_MAX_STALENESS"
PUBLISH_EVERY_ENV = "BLUEFOG_SERVE_PUBLISH_EVERY"
COMPRESS_ENV = "BLUEFOG_SERVE_COMPRESS"

DEFAULT_WINDOW_NAME = "bf_serving_params"


def resolve_max_staleness(value: Optional[int] = None) -> int:
    """``BLUEFOG_SERVE_MAX_STALENESS`` (steps, default 4): the bound past
    which a replica refuses to serve and the router stops selecting it."""
    if value is not None:
        return int(value)
    return int(os.environ.get(MAX_STALENESS_ENV, "4"))


def resolve_publish_every(value: Optional[int] = None) -> int:
    """``BLUEFOG_SERVE_PUBLISH_EVERY`` (steps, default 1): cadence of
    :meth:`WeightPublisher.maybe_publish`."""
    if value is not None:
        return max(1, int(value))
    return max(1, int(os.environ.get(PUBLISH_EVERY_ENV, "1")))


def serving_topology(publishers: Sequence[int], replicas: Sequence[int],
                     size: Optional[int] = None,
                     edges: Optional[Sequence[Tuple[int, int]]] = None
                     ) -> CompiledTopology:
    """Compile the publisher->replica parameter-window graph.

    Default: the full bipartite graph (every publisher feeds every
    replica, weight ``1/in_degree`` per edge, diagonal 1) — any replica
    then survives any single publisher death without a feed change.
    ``edges`` restricts it to explicit ``(publisher, replica)`` pairs
    (dedicated feeds; a starved replica is then a *designed* staleness
    scenario, which the smoke gate uses).  The graph spans the full mesh
    — non-serving ranks are isolated vertices with self weight 1, so the
    window's SPMD programs keep the mesh shape.
    """
    from ..context import is_initialized
    if size is None:
        size = ctx().size if is_initialized() else (
            max(list(publishers) + list(replicas)) + 1)
    pubs, reps = list(dict.fromkeys(publishers)), list(dict.fromkeys(replicas))
    if not pubs or not reps:
        raise ValueError("need at least one publisher and one replica")
    overlap = set(pubs) & set(reps)
    if overlap:
        raise ValueError(
            f"ranks {sorted(overlap)} are both publisher and replica; a "
            f"serving rank folds the window, a training rank overwrites "
            f"it — the roles must be disjoint")
    for r in pubs + reps:
        if not 0 <= r < size:
            raise ValueError(f"rank {r} outside [0, {size})")
    if edges is None:
        edges = [(p, r) for r in reps for p in pubs]
    # dedupe: a repeated pair would inflate indeg while W[p, r] is
    # assigned (not summed), silently under-weighting the fold
    edges = list(dict.fromkeys((int(p), int(r)) for p, r in edges))
    for p, r in edges:
        if p not in pubs or r not in reps:
            raise ValueError(
                f"edge {(p, r)} does not run publisher -> replica "
                f"(publishers {pubs}, replicas {reps})")
    fed = {r for _, r in edges}
    unfed = [r for r in reps if r not in fed]
    if unfed:
        raise ValueError(
            f"replicas {unfed} have no publisher edge; every replica "
            f"needs at least one feed")
    W = np.eye(size)
    indeg = {r: sum(1 for _, d in edges if d == r) for r in reps}
    for p, r in edges:
        W[p, r] = 1.0 / indeg[r]
    return compile_weight_matrix(W)


class WeightPublisher:
    """Continuously publish training weights onto the parameter window.

    ``params`` (the creation template) and every later ``publish`` input
    are GLOBAL-VIEW trees (leading dim = mesh size) — the standard shape
    every optimizer in this repo trains in.  Only publisher rows are
    read; replica and bystander rows of the input are ignored (the put
    merges the window's own rows back in so a publish never clobbers a
    replica's folded serving weights — ``win_put`` replaces the whole
    window tensor with its input).

    ``compression``: wire codec spec for the window transfers (default
    ``BLUEFOG_SERVE_COMPRESS``, off).  Dense quantizers only — the
    window layer itself rejects sparsifiers/choco with guidance
    (docs/compression.md, docs/serving.md "Rejected combinations").
    """

    def __init__(self, params, publishers: Sequence[int],
                 replicas: Sequence[int], *,
                 name: str = DEFAULT_WINDOW_NAME,
                 compression=None,
                 topo: Optional[CompiledTopology] = None,
                 edges: Optional[Sequence[Tuple[int, int]]] = None,
                 publish_every: Optional[int] = None,
                 standby: Sequence[int] = ()):
        cx = ctx()
        self.name = name
        self.publishers = list(dict.fromkeys(publishers))
        self.replicas = list(dict.fromkeys(replicas))
        # standby replicas (elastic autoscaling, docs/serving.md
        # "Replica autoscaling"): pre-allocated in the window topology —
        # their buffer slots, fold rows, and edges exist from creation,
        # so admitting one later (ReplicaSet.admit / RequestRouter.admit)
        # is pure host bookkeeping on the SAME compiled window programs,
        # zero recompiles.  They fold publications like any replica
        # (staying warm) but serve no traffic until admitted.
        self.standby = [r for r in dict.fromkeys(standby)
                        if r not in self.replicas]
        overlap = set(self.standby) & set(self.publishers)
        if overlap:
            raise ValueError(
                f"standby ranks {sorted(overlap)} are also publishers; "
                f"standby replicas must be replica-side capacity")
        self.publish_every = resolve_publish_every(publish_every)
        if compression is None:
            # serving default is OFF unless BLUEFOG_SERVE_COMPRESS names a
            # codec: falling through to the training-wide
            # BLUEFOG_COMM_COMPRESS would hand the window a sparsifier
            # spec it must reject
            compression = os.environ.get(COMPRESS_ENV) or False
        self.compression = _compress.resolve_compression(compression)
        if topo is not None and edges is not None:
            raise ValueError(
                "pass either topo= (a pre-compiled window graph) or "
                "edges= (pairs for serving_topology), not both — edges "
                "would be silently ignored")
        self.topo = topo if topo is not None else serving_topology(
            self.publishers, self.replicas + self.standby, size=cx.size,
            edges=edges)
        # a caller-supplied topo skipped serving_topology's checks: a
        # replica with no publisher in-edge would never gain a watermark
        # and be silently unroutable forever (standby included: a
        # feedless capacity slot could never be admitted warm)
        unfed = [r for r in self.replicas + self.standby
                 if not any(p in self.publishers
                            for p in self.topo.in_neighbor_ranks(r))]
        if unfed:
            raise ValueError(
                f"replicas {unfed} have no publisher in-edge on the "
                f"window topology; every replica needs at least one feed")
        # False (not None) when off: the window layer's own None falls
        # through to BLUEFOG_COMM_COMPRESS, which may name a sparsifier
        if not _win.win_create(params, name, topo=self.topo,
                               compression=(self.compression
                                            if self.compression is not None
                                            else False)):
            raise ValueError(
                f"window {name!r} already exists; win_free it or pick a "
                f"distinct serving window name")
        # the stream's version header: training step each publisher rank
        # most recently shipped (None = never published)
        self.last_published: Dict[int, Optional[int]] = {
            p: None for p in self.publishers}
        mask = np.zeros((cx.size,), np.float32)
        mask[self.publishers] = 1.0
        self._pub_mask = jnp.asarray(mask)

    # -- publishing ---------------------------------------------------------

    def _merged_input(self, params):
        """Publisher rows from ``params``, every other row from the
        window's current tensor — so the put's tensor replacement keeps
        replica folds and bystander rows intact."""
        current = _win.win_fetch(self.name)
        def merge(new, old):
            m = self._pub_mask.reshape(
                (-1,) + (1,) * (new.ndim - 1)).astype(bool)
            return jnp.where(m, jnp.asarray(new, old.dtype), old)
        return jax.tree.map(merge, params, current)

    def publish(self, params, step: int, alive=None) -> int:
        """One compressed nonblocking ``win_put`` of every live
        publisher's current weights; returns the op handle (``win_wait``
        it, or let the replica-side ``refresh`` flush it).

        ``alive`` (optional [N] mask): dead publishers ship nothing —
        their out-edges drop from the put's destination matrix, so their
        replicas' version counters stop advancing and staleness starts
        accruing, exactly as a crashed training process would look.
        """
        alive_row = None if alive is None else np.asarray(
            alive, np.float64).reshape(-1)
        # ship with weight 1.0 on every edge: the buffer holds the
        # publisher's VALUE, and the replica-side fold owns the
        # 1/in_degree averaging — weighting both sides would square it
        D = (self.topo.weight_matrix != 0).astype(np.float64)
        np.fill_diagonal(D, 0.0)
        if alive_row is not None:
            D = D * alive_row[:, None]
        handle = _win.win_put_nonblocking(
            self._merged_input(params), self.name,
            self_weight=1.0, dst_weights=D)
        for p in self.publishers:
            if alive_row is None or alive_row[p] > 0:
                self.last_published[p] = int(step)
        if _metrics.enabled():
            _metrics.counter(
                "bf_serve_publishes_total",
                "parameter-window weight publications (serving tier)"
            ).inc()
        return handle

    def maybe_publish(self, params, step: int, alive=None) -> Optional[int]:
        """Cadence-gated :meth:`publish` (``BLUEFOG_SERVE_PUBLISH_EVERY``)."""
        if step % self.publish_every == 0:
            return self.publish(params, step, alive=alive)
        return None

    # -- lifecycle ----------------------------------------------------------

    def in_publishers(self, replica: int) -> List[int]:
        """The publisher ranks feeding ``replica`` on the window graph."""
        return [p for p in self.topo.in_neighbor_ranks(replica)
                if p in self.publishers]

    def close(self) -> None:
        _win.win_free(self.name)
