"""Host-side request router: liveness + staleness + measured edge cost.

The :class:`RequestRouter` is the serving tier's front door.  Every
request is routed to ONE replica chosen from the currently *eligible*
set — alive (per the router's accrual-style liveness beliefs, reusing
``resilience.LivenessConfig`` thresholds) and within the staleness bound
— ordered sticky-first (the previous target keeps traffic while it
stays eligible; no flapping), then by staleness, then by measured edge
cost from the client-facing rank (a ``commprof.EdgeCostMatrix``,
consulted only when ``matrix_is_usable`` accepts it — a synthetic or
stale matrix must not steer production traffic), then by rank.

**Failover** is the event of the sticky target changing because it had
to: the current replica died (a :class:`~.replica.ReplicaDeadError`
from the serve attempt — the connection-refused analog — or the
liveness beliefs confirming a death) or aged past the staleness bound.
The failed request is retried on the next candidate in the same
``route`` call, so a single rank death costs ZERO failed requests once
the death is observable; each failover lands in the serving trail as a
``serve_failover`` record and on ``bf_serve_failovers_total``.

**The serving trail** is a sidecar JSONL at ``<prefix>serving.jsonl``
(same pattern as the controller's decision trail): a ``serve_config``
head record, periodic ``serve`` records (``serve_staleness`` per
replica, ``requests_per_s``, cumulative hit counts, fold latency), and
``serve_failover`` events — the machine-readable feed ``bfmonitor
--serving`` renders and ``validate_jsonl`` gates.
"""

import math
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability import export as _export
from ..observability import metrics as _metrics
from ..resilience import LivenessConfig
from .replica import ReplicaDeadError, ReplicaSet, StaleReplicaError

__all__ = ["RequestRouter", "NoReplicaAvailable", "FailoverEvent",
           "SERVING_SUFFIX", "read_serving_trail"]

SERVING_SUFFIX = "serving.jsonl"


class NoReplicaAvailable(RuntimeError):
    """Every replica is dead or past the staleness bound."""


class FailoverEvent:
    """One sticky-target switch, host-time-stamped for the trail."""

    __slots__ = ("step", "replica_from", "replica_to", "reason")

    def __init__(self, step: int, replica_from: int,
                 replica_to: Optional[int], reason: str):
        self.step = step
        self.replica_from = replica_from
        self.replica_to = replica_to
        self.reason = reason

    def asdict(self) -> dict:
        return {"step": self.step, "replica_from": self.replica_from,
                "replica_to": self.replica_to, "reason": self.reason}


def _serving_trail(path: str) -> "_export.Trail":
    """The serving JSONL rides the shared sidecar-trail writer
    (``observability.export.Trail``: size-based rotation, the
    ``serve_config`` head record re-written after every rotation so a
    rotated trail never orphans its records from the tier's identity)."""
    return _export.Trail(path, head_kind="serve_config")


def read_serving_trail(path: str):
    """Tolerant reader: ``(config_record_or_None, records)`` — the same
    contract as the controller's ``read_decisions``, via the shared
    sidecar-trail helper (a monitor frame must render a partial or
    damaged trail, never crash on it)."""
    return _export.read_trail(path, "serve_config")


class RequestRouter:
    """Distribute inference requests across a :class:`ReplicaSet`.

    ``prefix``: metrics prefix — the serving trail opens at
    ``<prefix>serving.jsonl`` (or pass ``trail_path`` directly; None
    keeps the router trail-less).  ``cost_matrix``: a measured
    :class:`~..observability.commprof.EdgeCostMatrix`; consulted as the
    final tie-break from ``client_rank`` to each replica, and only when
    ``matrix_is_usable`` accepts it (refusals count on
    ``bf_serve_refused_matrix_total``).  ``liveness``: suspect/confirm
    thresholds for the router's host-side death beliefs (defaults to
    ``resilience.LivenessConfig()``).

    Liveness observations arrive either as explicit masks
    (:meth:`observe`) or straight off the fabric via
    :meth:`observe_plane` — the in-band telemetry plane's local fleet
    view, which also refreshes the measured cost map from
    plane-gossiped edge rows when a usable matrix can be assembled.
    """

    def __init__(self, replicas: ReplicaSet, *,
                 prefix: Optional[str] = None,
                 trail_path: Optional[str] = None,
                 cost_matrix=None, client_rank: int = 0,
                 liveness: Optional[LivenessConfig] = None):
        self.replicas = replicas
        self.liveness = liveness or LivenessConfig()
        self.client_rank = int(client_rank)
        self.current: Optional[int] = None
        self.hits: Dict[int, int] = {r: 0 for r in replicas.replicas}
        self.refused = 0
        self.failovers: List[FailoverEvent] = []
        self.staleness_samples: List[float] = []
        # accrual beliefs: last step each replica was observed alive
        # (everyone starts alive, like membership.init_state); -inf is
        # the hard-confirmed state a connection error forces.  Beliefs
        # age against the newest OBSERVATION, not the request step — a
        # router nobody feeds liveness data stays optimistic instead of
        # confirming the whole fleet dead by timeout.
        self._last_ok: Dict[int, float] = {r: 0.0 for r in replicas.replicas}
        self._last_obs: float = 0.0
        self._cost = self._resolve_cost(cost_matrix)
        self._requests_window = 0
        self._window_t0 = time.perf_counter()
        path = trail_path or (prefix + SERVING_SUFFIX if prefix else None)
        self.trail = _serving_trail(path) if path else None
        if self.trail:
            self.trail.write({
                "kind": "serve_config",
                "replicas": list(replicas.replicas),
                "publishers": list(replicas.publisher.publishers),
                "max_staleness": replicas.max_staleness,
                "client_rank": self.client_rank,
                "window": replicas.name,
            })

    def _resolve_cost(self, matrix) -> Dict[int, float]:
        """Replica -> one-way latency from the client rank, from a
        USABLE measured matrix only."""
        self._matrix = None
        if matrix is None:
            return {}
        from ..observability import commprof as _cprof
        ok, why = _cprof.matrix_is_usable(matrix)
        if not ok:
            if _metrics.enabled():
                _metrics.counter(
                    "bf_serve_refused_matrix_total",
                    "edge-cost matrices the router refused to consult"
                ).inc()
            return {}
        # kept for replicas admitted later (elastic autoscaling): a new
        # replica's edge must be priced from the same accepted matrix
        self._matrix = matrix
        out = {}
        for r in self.replicas.replicas:
            lat = self._edge_cost(matrix, r)
            if lat is not None:
                out[r] = lat
        return out

    def _edge_cost(self, matrix, rank: int) -> Optional[float]:
        lat = matrix.latency_us(self.client_rank, rank)
        if lat is None:
            lat = matrix.latency_us(rank, self.client_rank)
        return None if lat is None else float(lat)

    # -- elastic admission (autoscaling hook) -------------------------------

    def admit(self, rank: int, step: int) -> None:
        """Admit a freshly-joined replica into the routing set — the
        serving tier's elastic-membership hook (docs/serving.md
        "Replica autoscaling").  Activates the standby rank on the
        :class:`~.replica.ReplicaSet` (pre-allocated window slots: zero
        recompiles), registers it with the router's liveness beliefs,
        hit counters, and measured edge costs, and records a
        ``serve_admit`` trail event.  The new replica joins the
        candidate order immediately; it WINS traffic only once its
        folded staleness enters the bound — the syncing → active half
        of the admission protocol happens in the folds."""
        if rank in self.replicas.standby:
            self.replicas.admit(rank)
        elif rank not in self.replicas.replicas:
            raise ValueError(
                f"rank {rank} is neither active nor standby on this "
                f"ReplicaSet (replicas {self.replicas.replicas}, "
                f"standby {self.replicas.standby})")
        self.hits.setdefault(rank, 0)
        # an admission is a liveness observation FOR THIS RANK only: it
        # must not advance the global observation clock (_last_obs), or
        # admitting capacity would age every replica nobody explicitly
        # feeds liveness data for into confirmed-dead — the router stays
        # optimistic about unobserved ranks by design (see __init__)
        self._last_ok[rank] = max(float(step), self._last_obs)
        if self._matrix is not None and rank not in self._cost:
            lat = self._edge_cost(self._matrix, rank)
            if lat is not None:
                self._cost[rank] = lat
        if self.trail:
            self.trail.write({"kind": "serve_admit", "step": int(step),
                              "replica": int(rank)})

    def retire(self, rank: int, step: int) -> None:
        """Orderly scale-down: move ``rank`` back to standby and out of
        the candidate set, recording a ``serve_retire`` trail event.
        Unlike a death there is no failover noise — the next ``route``
        simply re-picks among the remaining replicas."""
        self.replicas.retire(rank)
        if self.current == rank:
            self.current = None
        if self.trail:
            self.trail.write({"kind": "serve_retire", "step": int(step),
                              "replica": int(rank)})

    # -- liveness beliefs ---------------------------------------------------

    def observe(self, alive, step: int) -> None:
        """Feed one liveness observation (e.g. a fault plan's
        ``alive_at`` row, or ``membership`` beliefs collapsed to a
        mask).  A replica unseen for ``confirm_after`` steps is
        confirmed dead and leaves the candidate set."""
        row = np.asarray(alive).reshape(-1)
        self._last_obs = max(self._last_obs, float(step))
        for r in self.replicas.replicas:
            if row[r] > 0:
                self._last_ok[r] = float(step)

    def observe_plane(self, view, step: Optional[int] = None) -> None:
        """Feed liveness/staleness from the in-band telemetry plane
        (docs/observability.md "In-band telemetry plane"): ``view`` is
        this rank's :class:`~..observability.plane.FleetViewLive` — no
        shared filesystem, no central collector, just the local gossiped
        table.  Plane age within ``liveness.suspect_after`` counts as an
        alive observation (the router's own ``confirm_after`` accrual
        still governs death, so a briefly-quiet source is suspected, not
        executed).  When live sources carried measured edge-cost
        fragments, the routing cost map is refreshed from the assembled
        plane matrix — behind the same ``matrix_is_usable`` gate as a
        file artifact, with the plane's max source age as the freshness
        bound."""
        if step is None:
            step = view.plane_step
        self.observe(view.alive_mask(self.liveness.suspect_after), step)
        from ..observability import commprof as _cprof
        from ..observability import plane as _plane
        matrix = _plane.matrix_from_view(view)
        if matrix is None:
            return
        ages = [m["age"] for m in view.per_source.values()
                if not m["stale"]]
        ok, _why = _cprof.matrix_is_usable(
            matrix, age_steps=max(ages, default=0))
        if not ok:
            if _metrics.enabled():
                _metrics.counter(
                    "bf_serve_refused_matrix_total",
                    "edge-cost matrices the router refused to consult"
                ).inc()
            return
        self._matrix = matrix
        self._cost = {}
        for r in self.replicas.replicas:
            lat = self._edge_cost(matrix, r)
            if lat is not None:
                self._cost[r] = lat

    def confirmed_dead(self, rank: int, step: int) -> bool:
        return (self._last_obs - self._last_ok[rank]
                ) > self.liveness.confirm_after

    def _mark_dead(self, rank: int) -> None:
        # a connection error is instant confirmation — no accrual wait
        self._last_ok[rank] = -math.inf

    # -- selection ----------------------------------------------------------

    def _candidates(self, step: int) -> List[int]:
        """Eligible replicas, best first: sticky current, then
        (staleness, measured cost, rank)."""
        elig = [r for r in self.replicas.replicas
                if not self.confirmed_dead(r, step)
                and self.replicas.can_serve(r, step)]
        # unmeasured edges sort LAST (inf), not first: an edge the probe
        # never priced must not beat a measured one by defaulting cheap
        elig.sort(key=lambda r: (self.replicas.staleness_of(r, step),
                                 self._cost.get(r, math.inf), r))
        if self.current in elig:
            elig.remove(self.current)
            elig.insert(0, self.current)
        return elig

    def _failover(self, step: int, frm: int, to: Optional[int],
                  reason: str) -> None:
        ev = FailoverEvent(step, frm, to, reason)
        self.failovers.append(ev)
        if _metrics.enabled():
            _metrics.counter(
                "bf_serve_failovers_total",
                "sticky serving-target switches forced by death or "
                "staleness").inc(reason=reason)
        if self.trail:
            self.trail.write({"kind": "serve_failover", **ev.asdict()})

    # -- routing ------------------------------------------------------------

    def route(self, batch, step: int, alive=None):
        """Answer one request: returns ``(output, replica_rank)``.

        The request is retried down the candidate order on a dead
        target; a staleness breach of the sticky target re-routes
        BEFORE any attempt (the bound is checked, not discovered).
        Raises :class:`NoReplicaAvailable` (and counts
        ``bf_serve_unroutable_total``) when no replica is eligible.
        """
        if alive is not None:
            self.observe(alive, step)
        prev = self.current
        cands = self._candidates(step)
        # failover events are emitted AFTER the retry loop resolves, so
        # replica_to names the replica that actually took the traffic
        # (recording the pre-attempt selection could name a dead one)
        pending: List[tuple] = []
        if prev is not None and prev not in cands:
            # sticky target became ineligible between requests
            pending.append((prev, "dead" if self.confirmed_dead(prev, step)
                            else "stale"))
            self.current = None
        for r in cands:
            try:
                out = self.replicas.serve(r, batch, step, alive=alive)
            except ReplicaDeadError:
                self._mark_dead(r)
                if r == self.current:
                    # only the STICKY target's death is a failover — a
                    # dead never-used candidate just leaves the set
                    pending.append((r, "dead"))
                    self.current = None
                continue
            except StaleReplicaError:
                # raced a watermark change; the next candidate is already
                # ordered fresher
                continue
            for frm, reason in pending:
                self._failover(step, frm, r, reason)
            self.current = r
            self.hits[r] += 1
            self._requests_window += 1
            self.staleness_samples.append(
                self.replicas.staleness_of(r, step))
            return out, r
        for frm, reason in pending:
            self._failover(step, frm, None, reason)   # total outage
        self.refused += 1
        if _metrics.enabled():
            _metrics.counter(
                "bf_serve_unroutable_total",
                "requests refused: no live replica within the "
                "staleness bound").inc()
        raise NoReplicaAvailable(
            f"no replica eligible at step {step}: staleness "
            f"{self.replicas.staleness(step)} (bound "
            f"{self.replicas.max_staleness})")

    # -- reporting ----------------------------------------------------------

    def requests_per_s(self) -> float:
        """Request rate since the previous :meth:`log` call."""
        dt = time.perf_counter() - self._window_t0
        return self._requests_window / dt if dt > 0 else 0.0

    def log(self, step: int) -> Optional[dict]:
        """Append one periodic ``serve`` record to the trail (and reset
        the requests/sec window).  Returns the record written."""
        rps = self.requests_per_s()
        stale = self.replicas.staleness(step)
        record = {
            "kind": "serve",
            "step": int(step),
            "serve_staleness": {
                str(r): (s if math.isfinite(s) else -1.0)
                for r, s in stale.items()},
            "requests_per_s": round(rps, 3),
            "hits": {str(r): h for r, h in self.hits.items()},
            "refused": self.refused,
            "failovers": len(self.failovers),
            "current": self.current,
        }
        if self.replicas.last_fold_s is not None:
            record["fold_s"] = round(self.replicas.last_fold_s, 6)
        self._requests_window = 0
        self._window_t0 = time.perf_counter()
        if _metrics.enabled():
            _metrics.gauge(
                "bf_serve_requests_per_s",
                "request rate over the last reporting window").set(rps)
        if self.trail:
            return self.trail.write(record)
        return record

    def close(self) -> None:
        if self.trail:
            self.trail.close()
