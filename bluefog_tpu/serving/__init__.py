"""Decentralized serving tier: bounded-staleness weight replication,
request routing, chaos-tested failover.

The ROADMAP's "millions of users" workload — the first end-to-end
product surface that composes the window subsystem, wire compression,
resilience liveness masks, and the observability stack as ONE scenario:

* :mod:`~.publisher` — training ranks continuously publish weights via
  compressed nonblocking ``win_put`` on a dedicated parameter window
  (its own publisher->replica graph, ``win_create(topo=)``); dense
  quantizers are wire-legal on windows, sparsifiers are rejected by the
  window layer (docs/serving.md "Rejected combinations").
* :mod:`~.replica`   — serving ranks fold incoming versions with
  **bounded staleness**: per-replica version/step watermarks, folds via
  ``win_update(alive=)`` so a dead publisher degrades to self-weight
  instead of poisoning the fold, and a hard refusal to serve past
  ``BLUEFOG_SERVE_MAX_STALENESS``.
* :mod:`~.router`    — a host-side request router distributing batched
  inference requests by liveness + staleness + measured edge cost
  (``commprof.EdgeCostMatrix`` behind the shared ``matrix_is_usable``
  guard), with retry-through failover of a dead serving rank and a
  sidecar JSONL trail (``<prefix>serving.jsonl``) that ``bfmonitor
  --serving`` renders.

Entry points: ``examples/decentralized_serving.py``, ``bench.py
--serve`` (requests/sec + staleness percentiles), ``make serve-smoke``
(the chaos-failover CI gate).  See docs/serving.md.
"""

from .publisher import (
    COMPRESS_ENV,
    DEFAULT_WINDOW_NAME,
    MAX_STALENESS_ENV,
    PUBLISH_EVERY_ENV,
    WeightPublisher,
    resolve_max_staleness,
    resolve_publish_every,
    serving_topology,
)
from .replica import ReplicaDeadError, ReplicaSet, StaleReplicaError
from .router import (
    SERVING_SUFFIX,
    FailoverEvent,
    NoReplicaAvailable,
    RequestRouter,
    read_serving_trail,
)

__all__ = [
    "COMPRESS_ENV", "DEFAULT_WINDOW_NAME", "MAX_STALENESS_ENV",
    "PUBLISH_EVERY_ENV", "WeightPublisher", "resolve_max_staleness",
    "resolve_publish_every", "serving_topology",
    "ReplicaDeadError", "ReplicaSet", "StaleReplicaError",
    "SERVING_SUFFIX", "FailoverEvent", "NoReplicaAvailable",
    "RequestRouter", "read_serving_trail",
]
