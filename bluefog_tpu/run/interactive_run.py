"""``ibfrun`` — interactive bluefog_tpu session (reference:
``run/interactive_run.py``).

The reference spins up an ipyparallel cluster (one engine per MPI rank) so a
notebook can drive distributed code interactively.  Under single-controller
SPMD one interpreter already drives every device, so ``ibfrun`` reduces to:
configure the device view (virtual CPU devices if requested), call
``bf.init()``, and drop into a REPL (IPython when available) with ``bf``,
``jax`` and ``jnp`` bound.  ``ibfrun start/stop`` subcommands are accepted
for reference CLI compatibility and map to entering/exiting the session.
"""

import argparse
import os
import sys


def parse_args(argv):
    parser = argparse.ArgumentParser(
        prog="ibfrun", description="Interactive BlueFog-TPU session")
    parser.add_argument("subcommand", nargs="?", default="start",
                        choices=["start", "stop"],
                        help="reference-compatible; 'stop' is a no-op (the "
                             "session dies with the REPL)")
    parser.add_argument("-np", "--num-proc", type=int, default=None)
    parser.add_argument("--platform", default=None, choices=["tpu", "cpu"])
    parser.add_argument("--extra-script", default=None,
                        help="python file executed in the session namespace "
                             "before the prompt")
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    if args.subcommand == "stop":
        print("ibfrun: nothing to stop (sessions end with their REPL)")
        return 0

    if args.platform == "cpu" and args.num_proc:
        from .env_util import force_virtual_cpu_devices
        force_virtual_cpu_devices(os.environ, args.num_proc)

    import jax
    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import bluefog_tpu as bf

    bf.init()
    ns = {"bf": bf, "jax": jax, "jnp": jnp}
    banner = (f"bluefog_tpu interactive session — {bf.size()} device(s), "
              f"topology {type(bf.load_topology()).__name__}\n"
              f"bound names: bf, jax, jnp")
    if args.extra_script:
        with open(args.extra_script) as f:
            exec(compile(f.read(), args.extra_script, "exec"), ns)

    try:
        from IPython import start_ipython
        return start_ipython(argv=[], user_ns=ns,
                             display_banner=banner) or 0
    except ImportError:
        import code
        code.interact(banner=banner, local=ns)
        return 0


if __name__ == "__main__":
    sys.exit(main())
