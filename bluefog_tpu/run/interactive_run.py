"""``ibfrun`` — interactive bluefog_tpu session (reference:
``run/interactive_run.py:229-329``).

The reference spins up an **ipyparallel** cluster (one engine per MPI rank)
so a notebook can drive distributed code interactively, with hung-engine
SIGINT interrupts.  The TPU-native equivalent has two modes:

* **Local** (no ``-H``): single-controller SPMD — one interpreter already
  drives every device, so the session is a REPL with ``bf``/``jax``/``jnp``
  bound (IPython when available).
* **Multi-host** (``-H host1:N,host2:N``): a driver process binds a control
  socket and launches one *engine* per host with the same
  ``jax.distributed`` coordinator wiring as ``bfrun`` (run/run.py).  Every
  line typed at the driver is broadcast to ALL engines (multi-controller
  SPMD requires every process to execute the same program), each engine
  executes it in a persistent namespace and streams back its stdout, and
  the driver prints the outputs per engine.  ``Ctrl-C`` while waiting
  interrupts hung engines with SIGINT — the reference's hung-engine
  interrupt (interactive_run.py:229-265).  ``ibfrun stop`` tears down a
  cluster recorded in the pidfile.
"""

import argparse
import contextlib
import io
import json
import os
import shlex
import signal
import socket
import subprocess
import sys
import traceback
from typing import List, Optional

def _pidfile() -> str:
    """Resolved per call, not at import: an import-time read would freeze
    the path before a launcher could set ``BLUEFOG_IBFRUN_PIDFILE``
    (bflint: import-time-env-read)."""
    return os.environ.get("BLUEFOG_IBFRUN_PIDFILE",
                          "/tmp/bluefog_ibfrun.pids")


def parse_args(argv):
    parser = argparse.ArgumentParser(
        prog="ibfrun", description="Interactive BlueFog-TPU session")
    parser.add_argument("subcommand", nargs="?", default="start",
                        choices=["start", "stop", "engine"],
                        help="'start' opens a session; 'stop' tears down a "
                             "running multi-host cluster; 'engine' is "
                             "internal (worker loop)")
    parser.add_argument("-np", "--num-proc", type=int, default=None)
    parser.add_argument("-H", "--hosts", default=None,
                        help="comma-separated host:slots list — launches a "
                             "multi-host engine cluster like bfrun")
    parser.add_argument("-p", "--ssh-port", type=int, default=None)
    parser.add_argument("--platform", default=None, choices=["tpu", "cpu"])
    parser.add_argument("--coordinator-port", type=int, default=3390)
    parser.add_argument("--network-interface", default=None,
                        help="NIC for coordinator/DCN traffic (same "
                             "semantics as bfrun --network-interface)")
    parser.add_argument("--control-port", type=int, default=0,
                        help="driver control socket port (0 = ephemeral)")
    parser.add_argument("--control", default=None,
                        help="internal: engine's driver address host:port")
    parser.add_argument("--engine-id", type=int, default=None)
    parser.add_argument("--extra-script", default=None,
                        help="python file executed in the session namespace "
                             "before the prompt")
    parser.add_argument("--timeline-filename", default=None)
    parser.add_argument("--nodes-per-machine", type=int, default=None)
    parser.add_argument("--hostfile", default=None,
                        help="file with 'hostname slots=N' lines "
                             "(reference ibfrun -hostfile)")
    # Reference-compat flags (reference interactive_run.py:56-88) with
    # honest TPU-native semantics — same policy as bfrun's:
    parser.add_argument("--use-infiniband", action="store_true",
                        help="no-op on TPU (ICI/DCN transport is XLA's); "
                             "a note is printed")
    parser.add_argument("--extra-mpi-flags", default=None,
                        help="KEY=VAL entries exported to every engine's "
                             "environment (no mpirun underneath; raw "
                             "switches are rejected)")
    parser.add_argument("--ipython-profile", default=None,
                        help="accepted for reference compatibility; this "
                             "cluster is not ipyparallel-based, so the "
                             "profile name is unused (a note is printed)")
    parser.add_argument("--enable-heartbeat", action="store_true",
                        help="accepted for reference compatibility; hung-"
                             "engine detection is built in (the driver "
                             "SIGINT-interrupts engines stuck in user "
                             "code), so this is always on")
    parser.add_argument("--verbose", action="store_true")
    return parser.parse_args(argv)


# ---------------------------------------------------------------------------
# wire protocol: newline-delimited JSON over TCP
# ---------------------------------------------------------------------------

def _send(sock: socket.socket, obj) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode())


class _LineReader:
    def __init__(self, sock):
        self._f = sock.makefile("r")

    def recv(self) -> Optional[dict]:
        line = self._f.readline()
        if not line:
            return None
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            # a Ctrl-C can interrupt readline mid-line, losing its partial
            # bytes; surface the torn frame instead of crashing the driver
            return {"engine": "?", "stdout": "",
                    "error": "[driver] torn result line (interrupted read)"}


# ---------------------------------------------------------------------------
# engine (worker) side
# ---------------------------------------------------------------------------

def engine_main(control: str, engine_id: int) -> int:
    """Persistent exec loop: receive code, run it, stream stdout back.

    ``bf.init()`` runs on startup — the launcher set the jax.distributed
    coordinator env (BLUEFOG_COORDINATOR etc.), so every engine joins one
    global device mesh exactly like a bfrun worker."""
    host, port = control.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)))
    reader = _LineReader(sock)

    # The driver's hung-engine interrupt SIGINTs every engine; only an
    # engine stuck INSIDE user code should feel it.  Outside the exec
    # window (idle at recv, mid-send) the signal is swallowed — raising
    # there would kill a healthy engine or tear a half-written JSON line.
    in_exec = {"flag": False}

    def _sigint(_sig, _frm):
        if in_exec["flag"]:
            raise KeyboardInterrupt
    signal.signal(signal.SIGINT, _sigint)

    import jax
    import jax.numpy as jnp
    import bluefog_tpu as bf
    bf.init()
    ns = {"bf": bf, "jax": jax, "jnp": jnp}
    _send(sock, {"type": "ready", "engine": engine_id,
                 "size": bf.size(),
                 "process_index": jax.process_index()})

    while True:
        msg = reader.recv()
        if msg is None or msg.get("type") == "shutdown":
            break
        if msg.get("type") != "exec":
            continue
        buf = io.StringIO()
        error = None
        try:
            with contextlib.redirect_stdout(buf):
                try:
                    # 'single' echoes bare expressions like a REPL...
                    code_obj = compile(msg["code"], "<ibfrun>", "single")
                except SyntaxError:
                    # ...'exec' handles multi-statement blocks/scripts
                    code_obj = compile(msg["code"], "<ibfrun>", "exec")
                in_exec["flag"] = True
                exec(code_obj, ns)
        except BaseException:
            # drop the flag FIRST: a second Ctrl-C arriving while the
            # traceback is being formatted must not kill the engine
            in_exec["flag"] = False
            error = traceback.format_exc()
        finally:
            in_exec["flag"] = False
        _send(sock, {"type": "result", "engine": engine_id,
                     "stdout": buf.getvalue(), "error": error})
    bf.shutdown()
    return 0


# ---------------------------------------------------------------------------
# driver side
# ---------------------------------------------------------------------------

def _launch_engines(args, hosts, control_addr: str):
    """Spawn one engine per host with bfrun's coordinator wiring.

    Returns ``[(popen, host, is_local)]`` — for remote hosts the Popen is
    the *ssh client*, so signals must travel over a fresh ssh command (the
    control address doubles as a unique pkill pattern)."""
    from . import env_util, network_util
    from .run import _FORWARD_PREFIXES, _apply_common_flags, compat_flag_env

    any_remote = any(not network_util.is_local_host(h) for h, _ in hosts)
    try:
        coord_host = network_util.resolve_coordinator_host(
            hosts[0][0], getattr(args, "network_interface", None),
            getattr(args, "ssh_port", None), any_remote)
    except ValueError as e:
        # a typo'd --network-interface must exit cleanly, like bfrun
        raise SystemExit(f"ibfrun: {e}")
    coordinator = f"{coord_host}:{args.coordinator_port}"
    base_env = env_util.exportable_env()

    procs = []
    cwd = os.getcwd()
    for pid, (host, slots) in enumerate(hosts):
        env = _apply_common_flags(args, dict(base_env), slots)
        env.update({
            "BLUEFOG_COORDINATOR": coordinator,
            "BLUEFOG_NUM_PROCESSES": str(len(hosts)),
            "BLUEFOG_PROCESS_ID": str(pid),
        })
        cmd = [sys.executable, "-m", "bluefog_tpu.run.interactive_run",
               "engine", "--control", control_addr, "--engine-id", str(pid)]
        local = network_util.is_local_host(host)
        if local:
            procs.append((subprocess.Popen(cmd, env={**os.environ, **env}),
                          host, True))
        else:
            assigns = env_util.env_assignments(
                env, _FORWARD_PREFIXES, extra_keys=compat_flag_env(args))
            remote = (f"cd {shlex.quote(cwd)} && " + " ".join(assigns) + " "
                      + " ".join(shlex.quote(c) for c in cmd))
            ssh = ["ssh", "-o", "BatchMode=yes"]
            if args.ssh_port:
                ssh += ["-p", str(args.ssh_port)]
            procs.append((subprocess.Popen(ssh + [host, remote]),
                          host, False))
    return procs


def _remote_signal(host: str, control_addr: str, sig: str,
                   ssh_port=None) -> None:
    """Signal a remote engine by matching its unique control address (the
    local Popen is only the ssh client; signals do not ride the tunnel)."""
    cmd = ["ssh", "-o", "BatchMode=yes"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    cmd += [host, f"pkill -{sig} -f {shlex.quote(control_addr)}"]
    subprocess.run(cmd, capture_output=True, timeout=20)


def _interrupt_engines(procs, control_addr: str, ssh_port=None) -> None:
    """SIGINT to hung engines (reference interactive_run.py:229-265)."""
    for p, host, local in procs:
        if p.poll() is not None:
            continue
        if local:
            try:
                p.send_signal(signal.SIGINT)
            except OSError:
                pass
        else:
            _remote_signal(host, control_addr, "INT", ssh_port)


def driver_main(args, hosts) -> int:
    server = socket.socket()
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("0.0.0.0", args.control_port))
    server.listen(len(hosts))
    from . import network_util
    port_str = server.getsockname()[1]
    if any(not network_util.is_local_host(h) for h, _ in hosts):
        control_addr = f"{socket.gethostname()}:{port_str}"
    else:
        control_addr = f"127.0.0.1:{port_str}"

    procs = _launch_engines(args, hosts, control_addr)
    with open(_pidfile(), "w") as f:
        # "host pid ssh_port pattern" per line: ibfrun stop must reach
        # remote engines over ssh (the local pid is only the ssh client)
        for p, host, local in procs:
            f.write(f"{host} {p.pid} {args.ssh_port or '-'} "
                    f"{control_addr}\n")

    conns = []
    try:
        server.settimeout(5.0)
        deadline = 36  # 5s polls: generous for remote jax.distributed boot
        while len(conns) < len(hosts):
            try:
                conn, _ = server.accept()
                conns.append((conn, _LineReader(conn)))
            except socket.timeout:
                dead = [(host, p.poll()) for p, host, _ in procs
                        if p.poll() is not None]
                if dead:
                    raise SystemExit(
                        f"ibfrun: engine(s) died during startup: {dead} — "
                        f"check the coordinator port and worker logs")
                deadline -= 1
                if deadline <= 0:
                    raise SystemExit(
                        "ibfrun: timed out waiting for engines to connect")
        infos = [r.recv() for _, r in conns]
        if any(m is None for m in infos):
            raise SystemExit("ibfrun: an engine disconnected before "
                             "reporting ready (startup failure)")
        infos.sort(key=lambda m: m["engine"])
        n_eng = len(infos)
        print(f"ibfrun cluster up: {n_eng} engines, "
              f"{infos[0]['size']} global devices; every input line runs on "
              f"ALL engines (SPMD); Ctrl-C interrupts hung engines; "
              f"Ctrl-D exits", flush=True)

        interrupter = lambda: _interrupt_engines(procs, control_addr,
                                                 args.ssh_port)
        if args.extra_script:
            with open(args.extra_script) as f:
                _broadcast_and_print(conns, f.read(), interrupter)

        while True:
            try:
                line = input("ibf> " if sys.stdin.isatty() else "")
            except EOFError:
                break
            except KeyboardInterrupt:
                print("\n(^C at prompt discards the line; ^D exits)",
                      flush=True)
                continue
            if not line.strip():
                continue
            try:
                _broadcast_and_print(conns, line, interrupter)
            except KeyboardInterrupt:
                # last-resort net (the drain handles ^C itself and keeps
                # the reply stream in sync; reaching here means replies may
                # be misattributed to the next command)
                print("^C — interrupting engines (reply stream may be "
                      "desynced)", flush=True)
                interrupter()
    finally:
        for conn, _ in conns:
            try:
                _send(conn, {"type": "shutdown"})
            except OSError:
                pass
        for p, _, _ in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.terminate()
        server.close()
        if os.path.exists(_pidfile()):
            os.unlink(_pidfile())
    return 0


def _broadcast_and_print(conns, code: str, interrupter=None) -> None:
    pending = []
    for conn, reader in conns:
        try:
            _send(conn, {"type": "exec", "code": code})
            pending.append(reader)
        except OSError:
            pass      # dead engine: skipped rather than crashing the driver
    _drain(pending, interrupter)


def _drain(pending, interrupter=None) -> None:
    """Print each still-unanswered engine's result.  ``pending`` tracks
    exactly the connections owed a reply, so a Ctrl-C retry never re-reads
    an engine that already answered (that would block forever); the
    interrupt only SIGINTs engines and keeps waiting — interrupted execs
    come back as ordinary error results."""
    pending = list(pending)
    while pending:
        reader = pending[0]
        try:
            msg = reader.recv()
        except KeyboardInterrupt:
            if interrupter is None:
                raise
            print("^C — interrupting engines", flush=True)
            interrupter()
            continue
        except OSError:
            msg = None
        pending.pop(0)
        if msg is None:
            continue
        try:
            tag = f"[engine {msg.get('engine')}] "
            out = msg.get("stdout") or ""
            for ln in out.splitlines():
                print(tag + ln, flush=True)
            if msg.get("error"):
                for ln in msg["error"].splitlines():
                    print(tag + ln, flush=True)
        except KeyboardInterrupt:
            # ^C while printing: the message is already consumed (stream
            # stays in sync); signal the engines and keep draining the rest
            if interrupter is None:
                raise
            print("^C — interrupting engines", flush=True)
            interrupter()


def stop_main() -> int:
    if not os.path.exists(_pidfile()):
        print("ibfrun: no running cluster (no pidfile)")
        return 0
    from . import network_util
    n = 0
    with open(_pidfile()) as f:
        for line in f:
            if not line.strip():
                continue
            parts = line.split(None, 3)
            if len(parts) == 3:          # older 3-field pidfile format
                host, pid, pattern = parts
                ssh_port = "-"
            else:
                host, pid, ssh_port, pattern = parts
            n += 1
            if network_util.is_local_host(host):
                try:
                    os.kill(int(pid), signal.SIGTERM)
                except ProcessLookupError:
                    pass
            else:
                _remote_signal(host, pattern.strip(), "TERM",
                               None if ssh_port == "-" else int(ssh_port))
    os.unlink(_pidfile())
    print(f"ibfrun: stopped {n} engine(s)")
    return 0


# ---------------------------------------------------------------------------
# local single-controller session
# ---------------------------------------------------------------------------

def local_main(args) -> int:
    if args.platform == "cpu" and args.num_proc:
        from .env_util import force_virtual_cpu_devices
        force_virtual_cpu_devices(os.environ, args.num_proc)

    import jax
    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import bluefog_tpu as bf

    bf.init()
    ns = {"bf": bf, "jax": jax, "jnp": jnp}
    banner = (f"bluefog_tpu interactive session — {bf.size()} device(s), "
              f"topology {type(bf.load_topology()).__name__}\n"
              f"bound names: bf, jax, jnp")
    if args.extra_script:
        with open(args.extra_script) as f:
            exec(compile(f.read(), args.extra_script, "exec"), ns)

    try:
        from IPython import start_ipython
        return start_ipython(argv=[], user_ns=ns,
                             display_banner=banner) or 0
    except ImportError:
        import code
        code.interact(banner=banner, local=ns)
        return 0


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    if args.subcommand == "stop":
        return stop_main()
    if args.subcommand == "engine":
        if not args.control or args.engine_id is None:
            raise SystemExit("ibfrun engine: --control and --engine-id "
                             "are internal required flags")
        return engine_main(args.control, args.engine_id)
    # Compat-flag notes/validation once for every path — including the
    # local (no -H) session, which never builds per-engine envs: KEY=VAL
    # entries land in this process's environment so the in-process
    # session sees them exactly like a remote engine would.
    from .run import compat_flag_env
    args._prog = "ibfrun"
    os.environ.update(compat_flag_env(args))
    if args.hosts and args.hostfile:
        raise SystemExit("ibfrun: use either -H or --hostfile, not both")
    if args.hosts or args.hostfile:
        from . import network_util
        hosts = (network_util.parse_hostfile(args.hostfile)
                 if args.hostfile else
                 network_util.parse_host_spec(args.hosts))
        return driver_main(args, hosts)
    return local_main(args)


if __name__ == "__main__":
    sys.exit(main())
