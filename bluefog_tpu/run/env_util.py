"""Environment forwarding (reference: ``run/env_util.py`` — exportable-env
filtering so launcher state reaches every worker)."""

import os
import re
from typing import Dict, Iterable, List

# Never forward these across hosts: they are per-process/host identity.
_BLOCKLIST = re.compile(
    r"^(BASH_FUNC.*|HOSTNAME|PWD|OLDPWD|SHLVL|SSH_.*|DISPLAY|TMPDIR|"
    r"XDG_.*|LS_COLORS|_)$")


def is_exportable(name: str) -> bool:
    return _BLOCKLIST.match(name) is None


def exportable_env(env: Dict[str, str] = None) -> Dict[str, str]:
    env = dict(os.environ if env is None else env)
    return {k: v for k, v in env.items() if is_exportable(k)}


def force_virtual_cpu_devices(env: Dict[str, str], n: int) -> Dict[str, str]:
    """Configure ``env`` so a fresh JAX process sees ``n`` virtual CPU
    devices (the TPU analog of the reference's localhost oversubscription,
    Makefile:5-8).  Must reach the process before any backend initializes.
    An existing device-count flag is rewritten to ``n``, not kept."""
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    env["XLA_FLAGS"] = flags
    return env


def append_xla_flag(env: Dict[str, str], flag: str) -> Dict[str, str]:
    """Append ``--name=value`` to ``env['XLA_FLAGS']`` unless a flag with
    that name is already present (user wins).  Skipped entirely when
    ``BLUEFOG_NO_XLA_FLAG_INJECT`` is set — the escape hatch for XLA
    builds that do not know a flag (XLA fatals on unknown XLA_FLAGS).
    Must run before the first backend use."""
    if env.get("BLUEFOG_NO_XLA_FLAG_INJECT"):
        return env
    name = flag.lstrip("-").split("=", 1)[0]
    flags = env.get("XLA_FLAGS", "")
    # Compare against each existing token's extracted --name, not a raw
    # substring: a name that prefixes another flag's name (or appears in
    # a value) must not suppress injection.
    present = {tok.lstrip("-").split("=", 1)[0]
               for tok in flags.split() if tok.startswith("-")}
    if name not in present:
        env["XLA_FLAGS"] = (flags + " " + flag).strip()
    return env


_FLAG_PROBE_CACHE: Dict[str, bool] = {}


def _probe_cache_path() -> str:
    """On-disk probe verdicts, keyed by jaxlib version (flag support only
    changes with the XLA build) AND uid: one process pays the probe, every
    later pytest session / launcher / example reads the file.  Per-user,
    not world-shared — on a multi-user host a shared /tmp file would be
    poisonable by (and unwritable over from) other accounts."""
    import jaxlib
    import tempfile
    ver = getattr(jaxlib, "__version__", "unknown").replace("/", "_")
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(),
                        f"bluefog_xla_flag_probe_u{uid}_{ver}.json")


def _load_probe_cache() -> None:
    if _FLAG_PROBE_CACHE:
        return
    import json
    try:
        with open(_probe_cache_path()) as f:
            _FLAG_PROBE_CACHE.update({k: bool(v)
                                      for k, v in json.load(f).items()})
    except Exception:
        pass


def _store_probe_cache() -> None:
    import json
    try:
        tmp = _probe_cache_path() + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_FLAG_PROBE_CACHE, f)
        os.replace(tmp, _probe_cache_path())
    except Exception:
        pass


def _probe_subprocess(flags: str, timeout: int = 120) -> bool:
    import subprocess
    import sys
    env = dict(os.environ)
    env["XLA_FLAGS"] = flags
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BLUEFOG_EXPECTED_SIZE", None)
    try:
        return subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, capture_output=True, timeout=timeout).returncode == 0
    except Exception:
        return False


def xla_flags_supported(flags: List[str]) -> Dict[str, bool]:
    """Which of ``flags`` the installed XLA build knows.

    XLA *fatals the whole process* on an unknown name in ``XLA_FLAGS``
    (parse_flags_from_env.cc), so probing must run in a throwaway
    subprocess: initialize a 1-device CPU backend under the candidate
    flags and see whether it survives.  All un-cached flags are probed in
    ONE subprocess first (the common all-supported case costs a single
    cold import); only a combined failure falls back to per-flag probes.
    Verdicts persist on disk keyed by the jaxlib version.  Probe failures
    of any kind (abort, timeout) count as unsupported — skipping a tuning
    flag is always safe, injecting an unknown one never is."""
    _load_probe_cache()
    names = {flag: flag.lstrip("-").split("=", 1)[0] for flag in flags}
    todo = [f for f in flags if names[f] not in _FLAG_PROBE_CACHE]
    if todo:
        if _probe_subprocess(" ".join(todo)):
            for f in todo:
                _FLAG_PROBE_CACHE[names[f]] = True
        else:
            for f in todo:
                _FLAG_PROBE_CACHE[names[f]] = _probe_subprocess(f)
        _store_probe_cache()
    return {names[f]: _FLAG_PROBE_CACHE[names[f]] for f in flags}


def xla_flag_supported(flag: str) -> bool:
    """Single-flag convenience over :func:`xla_flags_supported`."""
    return next(iter(xla_flags_supported([flag]).values()))


# Async-collective / latency-hiding-scheduler candidates.  These are what
# turn the overlapped stepper's off-critical-path collectives
# (BLUEFOG_COMM_OVERLAP, docs/performance.md "Overlap") into actual
# start/done pairs the scheduler can move compute between.  Names vary by
# XLA build generation, hence the probe: anything the installed build does
# not know is skipped (an unknown XLA_FLAGS name is a process FATAL).
LATENCY_HIDING_FLAGS = [
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_enable_async_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion=true",
]


def latency_hiding_flags(env: Dict[str, str]) -> Dict[str, str]:
    """Probe-gate and append the async-collective / latency-hiding
    scheduler flags to ``env['XLA_FLAGS']``.

    Each candidate is checked against the installed XLA build first
    (:func:`xla_flags_supported`: one throwaway subprocess, disk-cached
    per jaxlib version) and appended only when known — injecting an
    unknown name would fatal the real process at first backend use, while
    skipping a tuning flag merely loses overlap.  User-set flags win
    (:func:`append_xla_flag` semantics); ``BLUEFOG_NO_XLA_FLAG_INJECT``
    or ``BLUEFOG_LATENCY_HIDING=0`` skips entirely.  Applied by the
    ``bfrun`` launcher for non-CPU platforms (``run.py``); call it
    yourself before first backend use for un-launched programs.
    Documented in docs/env_variable.md."""
    if env.get("BLUEFOG_NO_XLA_FLAG_INJECT"):
        return env
    if env.get("BLUEFOG_LATENCY_HIDING", "1") == "0":
        return env
    support = xla_flags_supported(LATENCY_HIDING_FLAGS)
    for flag in LATENCY_HIDING_FLAGS:
        if support[flag.lstrip("-").split("=", 1)[0]]:
            append_xla_flag(env, flag)
    return env


def arm_low_core_cpu_mitigations(env: Dict[str, str],
                                 terminate_timeout_s: int = 1200
                                 ) -> Dict[str, str]:
    """XLA:CPU mitigations for many-virtual-device runs on low-core hosts.

    (a) Raise the collective-rendezvous terminate timeout: one core
    staggers the device threads into each collective and the 40 s default
    mistakes that for deadlock.  (b) On <=2 cores, run Eigen inline: the
    shared intra-op pool can wedge conv-heavy 8-device programs outright
    (a device thread blocks in the pool and never reaches the
    collective).  Call before the first backend use; opt out with
    ``BLUEFOG_NO_XLA_FLAG_INJECT``.

    The flags are probed against the installed XLA build first
    (:func:`xla_flags_supported`; one subprocess, disk-cached per jaxlib
    version): older jaxlibs do not know these names and would abort the
    process at first backend use.  A dropped mitigation is announced on
    stderr — silently losing the anti-wedge timeout would be worse than
    the noise."""
    if env.get("BLUEFOG_NO_XLA_FLAG_INJECT"):
        return env
    flags = ([f"--xla_cpu_collective_call_terminate_timeout_seconds="
              f"{terminate_timeout_s}"]
             + (["--xla_cpu_multi_thread_eigen=false"]
                if (os.cpu_count() or 1) <= 2 else []))
    support = xla_flags_supported(flags)
    for flag in flags:
        if support[flag.lstrip("-").split("=", 1)[0]]:
            append_xla_flag(env, flag)
        else:
            import sys
            print(f"bluefog_tpu: XLA:CPU mitigation flag {flag} not "
                  f"supported by this XLA build (or probe failed) — "
                  f"skipped; low-core collective runs may hit the 40s "
                  f"rendezvous timeout", file=sys.stderr)
    return env


def env_assignments(env: Dict[str, str], only_prefixes: List[str],
                    extra_keys: Iterable[str] = ()) -> List[str]:
    """Shell-safe ``K=V`` assignments for the vars worth forwarding over ssh:
    anything matching the given prefixes (reference forwards -x env vars,
    run.py:186-198), plus ``extra_keys`` exactly (the --extra-mpi-flags
    KEY=VAL entries must reach remote workers too — prefix filtering
    would silently drop them)."""
    import shlex
    extra = set(extra_keys)
    out = []
    for k, v in sorted(env.items()):
        # extra keys bypass is_exportable: the operator explicitly asked
        # for them, and silently dropping a blocklisted name would
        # recreate the local/remote asymmetry this parameter exists to fix
        if (k in extra or (any(k.startswith(p) for p in only_prefixes)
                           and is_exportable(k))):
            out.append(f"{k}={shlex.quote(v)}")
    return out
