"""``bfmonitor`` — live terminal fleet dashboard over the JSONL series.

Tails the ``<prefix><rank>.jsonl`` metrics files a training fleet writes
(``BLUEFOG_METRICS=<prefix>``), aggregates them into the step-aligned
fleet view (``observability/aggregate.py``), runs the health engine
(``observability/health.py``), and renders a per-rank dashboard:
sparkline consensus/step-time columns, cross-rank spread stats, active
alerts, and the degraded-rank summary.

Modes::

    bfmonitor /tmp/series_                # live dashboard, 2 s refresh
    bfmonitor /tmp/series_ --once         # render one frame and exit
    bfmonitor /tmp/series_ --once --json  # machine-readable report (CI
                                          # gating: `make health-smoke`)
    bfmonitor /tmp/series_ --verdicts /tmp/verdicts.jsonl
                                          # also append HealthReports to
                                          # a verdict JSONL (controller
                                          # feed)

Exit status: 0 normally; with ``--fail-on warn`` (or ``critical``) a
``--once`` run exits 1 when a verdict at (or above) that severity is
active — the CI-gate contract.
"""

import argparse
import json
import math
import sys
import time
from typing import List, Optional

from ..observability import aggregate as AG
from ..observability import health as H

__all__ = ["main", "build_report", "render_dashboard", "sparkline",
           "render_checkpoint", "render_async", "render_plane",
           "render_edge_heatmap", "render_decisions", "render_serving",
           "render_membership"]

_TICKS = "▁▂▃▄▅▆▇█"
_SEV_TAG = {"critical": "CRIT", "warn": "warn", "info": "info"}


def sparkline(values: List[float], width: int = 12,
              log_scale: bool = False) -> str:
    """Unicode sparkline of the LAST ``width`` samples.  ``log_scale``
    suits geometric series (consensus distance spans decades); non-finite
    samples render as ``!``."""
    vals = values[-width:]
    if not vals:
        return ""
    finite = [v for v in vals if math.isfinite(v)]
    if not finite:
        return "!" * len(vals)
    if log_scale:
        floor = min((v for v in finite if v > 0), default=1.0)
        xform = lambda v: math.log10(max(v, floor * 1e-3))
        finite = [xform(v) for v in finite]
    else:
        xform = lambda v: v
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in vals:
        if not math.isfinite(v):
            out.append("!")
            continue
        x = xform(v)
        frac = 0.5 if span <= 0 else (x - lo) / span
        out.append(_TICKS[min(len(_TICKS) - 1,
                              max(0, int(frac * len(_TICKS))))])
    return "".join(out)


def _fmt(v: Optional[float], unit: str = "") -> str:
    if v is None:
        return "-"
    if not math.isfinite(v):
        return repr(v)
    if unit == "ms":
        return f"{v * 1e3:.1f}ms"
    if v != 0 and (abs(v) < 1e-3 or abs(v) >= 1e5):
        return f"{v:.2e}"
    return f"{v:.4g}"


def _strict_json(obj):
    """RFC 8259-safe copy: bare NaN/Infinity would make ``--json`` output
    unparseable by strict consumers (jq, the CI gate) on exactly the
    sick runs the monitor exists to diagnose — stringify them, same
    treatment as ``Verdict.asdict``."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)
    if isinstance(obj, dict):
        return {k: _strict_json(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_strict_json(v) for v in obj]
    return obj


def build_report(prefix: str, *, window: Optional[int] = None,
                 expected_ranks: Optional[int] = None,
                 verdicts_path: Optional[str] = None,
                 decisions_path: Optional[str] = None,
                 serving_path: Optional[str] = None,
                 membership_path: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 async_path: Optional[str] = None,
                 plane_path: Optional[str] = None,
                 fleet_path: Optional[str] = None,
                 cache: Optional[AG.TailCache] = None):
    """One monitoring pass: load the fleet view, evaluate health, and
    assemble the JSON-able report dict ``--once --json`` prints (the
    same dict `make health-smoke` asserts on).  Returns
    ``(view, health_report, report_dict)``.

    ``decisions_path``: the closed-loop controller's decision trail
    (default discovery: ``<prefix>decisions.jsonl`` — the path
    ``control.Controller`` writes) — its decisions render as the
    dashboard's decisions panel and ride the ``--json`` report.
    ``serving_path``: the serving tier's trail (default discovery:
    ``<prefix>serving.jsonl``, ``serving/router.py``) — replica
    staleness, request rate, and failover events become the
    ``"serving"`` block (a controller endpoint) and the ``--serving``
    panel.  ``membership_path``: the elastic-membership trail (default
    discovery: ``<prefix>membership.jsonl``,
    ``observability/export.py::MembershipTrail``) — per-rank membership
    states, active/syncing counts, and join/leave transitions become
    the ``"membership"`` block and the ``--membership`` panel.
    ``checkpoint_path``: the durable-fleet-state trail (default
    discovery: ``<prefix>ckpt.jsonl``,
    ``observability/export.py::CkptTrail``) — last durable step, save
    seconds/bytes, and commit-protocol events (torn shards, replica
    repairs, restores) become the ``"checkpoint"`` block and the
    ``--checkpoint`` panel.  ``async_path``: the async-training trail
    (default discovery: ``<prefix>async.jsonl``,
    ``observability/export.py::AsyncTrail``) — the cadence period
    vector, fired-rank and staleness series, push-sum P spread, and
    bounded-staleness refusals become the ``"async"`` block and the
    ``--async`` panel.  ``plane_path``: the in-band telemetry plane's
    trail (default discovery: ``<prefix>plane.jsonl``,
    ``observability/export.py::PlaneTrail``) — ONE rank's gossiped
    fleet view with per-source version/age/hop (stale sources flagged
    against ``BLUEFOG_PLANE_MAX_AGE``) becomes the ``"plane"`` block
    and the ``--plane`` panel, so the dashboard works from any single
    rank with no shared filesystem.  ``fleet_path``: the fleet
    supervisor's trail (default discovery: ``<prefix>fleet.jsonl``,
    ``observability/export.py::FleetTrail``) — per-rank pid, last
    heartbeat, respawn counts, and process-lifecycle/membership events
    become the ``"fleet"`` block and the ``--fleet`` panel
    (docs/running.md "Fleet mode")."""
    cfg = H.HealthConfig.from_env()
    if window:
        cfg.window = window
    view = AG.load_fleet(prefix, expected_ranks=expected_ranks,
                         cache=cache)
    report = H.evaluate(view, cfg)
    if verdicts_path:
        H.write_verdicts(report, verdicts_path)
    last = view.last_step()
    per_rank = {}
    for rank in view.ranks:
        cd = [x for x in view.series_of(rank, "consensus_dist")
              if x[1] != H.UNMEASURED]
        wall = view.step_wall_s(rank)
        per_rank[str(rank)] = {
            "last_step": view.rank_last_step(rank),
            "consensus_dist": cd[-1][1] if cd else None,
            "step_wall_s": wall[-1][1] if wall else None,
            "steps": len(view.per_rank.get(rank, {})),
        }
    spreads = {}
    if last is not None:
        for field in ("consensus_dist", "param_norm", "residual_norm",
                      "wire_bytes"):
            # a degraded no-collective step reports the -1 UNMEASURED
            # consensus sentinel — evidence, not a measurement
            st = view.fleet_spread(
                last, field,
                exclude=H.UNMEASURED if field == "consensus_dist" else None)
            if st is not None:
                spreads[field] = st.asdict()
        walls = [w[-1][1] for w in
                 (view.step_wall_s(r) for r in view.ranks) if w]
        st = AG.spread(walls)
        if st is not None:
            spreads["step_wall_s"] = st.asdict()
    # measured overlap efficiency: spread over each rank's LATEST probe
    # (probes are periodic, so per-step alignment would miss most ranks)
    effs = []
    for rank in view.ranks:
        series = view.series_of(rank, "overlap_efficiency")
        if series:
            effs.append(series[-1][1])
    st = AG.spread(effs)
    if st is not None:
        spreads["overlap_efficiency"] = st.asdict()
    out = {
        "prefix": prefix,
        "ok": report.ok,
        "ranks": len(view.ranks),
        "expected_ranks": view.expected_ranks,
        "last_step": last,
        "window": [report.step_lo, report.step_hi],
        "alerts": len(report.alerts),
        "verdicts": [v.asdict() for v in report.verdicts],
        "per_rank": per_rank,
        "spread": spreads,
        # the comm profiler's measured per-edge cost matrix (newest
        # "edges" record in the fleet) — with the spreads above this
        # makes the --once --json report the ONE controller feed: health
        # verdicts, cross-rank spreads, link costs, overlap efficiency
        "edges": view.latest_edges(),
        "gaps": [g.asdict() for g in view.gaps],
    }
    out["decisions"] = _decisions_block(prefix, decisions_path)
    out["serving"] = _serving_block(prefix, serving_path)
    out["membership"] = _membership_block(prefix, membership_path)
    out["checkpoint"] = _checkpoint_block(prefix, checkpoint_path)
    out["async"] = _async_block(prefix, async_path)
    out["plane"] = _plane_block(prefix, plane_path)
    out["fleet"] = _fleet_block(prefix, fleet_path)
    return view, report, _strict_json(out)


def _decisions_block(prefix: str,
                     decisions_path: Optional[str]) -> Optional[dict]:
    """The controller's decision trail as a report block: counts by
    ``knob:action`` plus the most recent records — None when no trail
    exists (a run without a controller stays noise-free)."""
    from ..control import DECISIONS_SUFFIX, read_decisions
    path = decisions_path or prefix + DECISIONS_SUFFIX
    config, decisions = read_decisions(path)
    if config is None and not decisions:
        return None
    counts = {}
    for d in decisions:
        key = f"{d.get('knob')}:{d.get('action')}"
        counts[key] = counts.get(key, 0) + 1
    return {
        "path": path,
        "mode": decisions[-1].get("mode") if decisions else None,
        "total": len(decisions),
        "counts": counts,
        "recent": decisions[-8:],
    }


def _serving_block(prefix: str,
                   serving_path: Optional[str]) -> Optional[dict]:
    """The serving tier's trail as a report block: per-replica staleness
    (latest + the trailing series the panel sparklines), router hit
    counts, request rate, and failover events — None when no trail
    exists (a run without a serving tier stays noise-free)."""
    from ..serving import SERVING_SUFFIX, read_serving_trail
    path = serving_path or prefix + SERVING_SUFFIX
    config, records = read_serving_trail(path)
    if config is None and not records:
        return None
    serves = [r for r in records if r.get("kind") == "serve"]
    failovers = [r for r in records if r.get("kind") == "serve_failover"]
    replicas = [str(r) for r in (config or {}).get("replicas", [])]
    if not replicas and serves:
        # rank order, not lexicographic: '10' must not sort before '2'
        replicas = sorted((serves[-1].get("serve_staleness") or {}).keys(),
                          key=lambda k: (0, int(k)) if k.isdigit()
                          else (1, k))
    staleness = {}
    for rep in replicas:
        series = [s["serve_staleness"][rep] for s in serves
                  if isinstance(s.get("serve_staleness"), dict)
                  and rep in s["serve_staleness"]]
        staleness[rep] = {
            "last": series[-1] if series else None,
            "series": series[-24:],
        }
    latest = serves[-1] if serves else {}
    return {
        "path": path,
        "window": (config or {}).get("window"),
        "max_staleness": (config or {}).get("max_staleness"),
        "replicas": replicas,
        "step": latest.get("step"),
        "requests_per_s": latest.get("requests_per_s"),
        "hits": latest.get("hits"),
        "refused": latest.get("refused"),
        "current": latest.get("current"),
        "staleness": staleness,
        "failovers": {
            "total": len(failovers),
            "recent": failovers[-4:],
        },
    }


def _membership_block(prefix: str,
                      membership_path: Optional[str]) -> Optional[dict]:
    """The elastic-membership trail as a report block: the latest
    per-rank state map, active/syncing count series (the panel
    sparklines them), and the recent join/leave transitions — None when
    no trail exists (a run without elasticity stays noise-free)."""
    from ..observability.export import (MEMBERSHIP_SUFFIX,
                                        read_membership_trail)
    path = membership_path or prefix + MEMBERSHIP_SUFFIX
    config, records = read_membership_trail(path)
    if config is None and not records:
        return None
    states = [r for r in records if r.get("kind") == "membership"]
    events = [r for r in records if r.get("kind") == "membership_event"]
    latest = states[-1] if states else {}
    series = {k: [s.get(k) for s in states
                  if isinstance(s.get(k), (int, float))]
              for k in ("active", "syncing")}
    return {
        "path": path,
        "size": (config or {}).get("size"),
        "capacity": (config or {}).get("capacity"),
        "step": latest.get("step"),
        "states": latest.get("states"),
        "active": latest.get("active"),
        "syncing": latest.get("syncing"),
        "active_series": series["active"][-24:],
        "syncing_series": series["syncing"][-24:],
        "events": {
            "total": len(events),
            "recent": events[-6:],
        },
    }


def _checkpoint_block(prefix: str,
                      checkpoint_path: Optional[str]) -> Optional[dict]:
    """The durable-fleet-state trail as a report block: the newest
    durable step, save accounting, and the commit-protocol event tally
    (torn shards, replica repairs, restores, skipped saves) — None when
    no trail exists (a run without checkpointing stays noise-free)."""
    from ..observability.export import CKPT_SUFFIX, read_ckpt_trail
    path = checkpoint_path or prefix + CKPT_SUFFIX
    config, records = read_ckpt_trail(path)
    if config is None and not records:
        return None
    saves = [r for r in records if r.get("kind") == "ckpt"]
    events = [r for r in records if r.get("kind") == "ckpt_event"]
    counts = {}
    for e in events:
        key = e.get("event")
        counts[key] = counts.get(key, 0) + 1
    latest = saves[-1] if saves else {}
    return {
        "path": path,
        "dir": (config or {}).get("dir"),
        "every": (config or {}).get("every"),
        "keep": (config or {}).get("keep"),
        "replicas": (config or {}).get("replicas"),
        "last_durable_step": latest.get("durable_step"),
        "bytes": latest.get("bytes"),
        "save_s": latest.get("save_s"),
        "shards": latest.get("shards"),
        "saves": len(saves),
        "save_s_series": [s.get("save_s") for s in saves
                          if isinstance(s.get("save_s"),
                                        (int, float))][-24:],
        "torn_shards": counts.get("torn_shard", 0),
        "replica_repairs": counts.get("replica_repair", 0),
        "restores": (counts.get("restore", 0)
                     + counts.get("elastic_restore", 0)),
        "skipped": counts.get("save_skipped", 0),
        "events": {
            "total": len(events),
            "counts": counts,
            "recent": events[-6:],
        },
    }


def _async_block(prefix: str, async_path: Optional[str]) -> Optional[dict]:
    """The async-training trail as a report block: the cadence period
    vector, fired-rank and effective-staleness series (the panel
    sparklines them), the push-sum P spread, and the scheduler's
    bounded-staleness refusal count — None when no trail exists (a
    synchronous run stays noise-free)."""
    from ..observability.export import ASYNC_SUFFIX, read_async_trail
    path = async_path or prefix + ASYNC_SUFFIX
    config, records = read_async_trail(path)
    if config is None and not records:
        return None
    ticks = [r for r in records if r.get("kind") == "async"]
    latest = ticks[-1] if ticks else {}
    series = {k: [t.get(k) for t in ticks
                  if isinstance(t.get(k), (int, float))]
              for k in ("active", "staleness_max")}
    return {
        "path": path,
        "size": (config or {}).get("size"),
        "max_staleness": (config or {}).get("max_staleness"),
        "step": latest.get("step"),
        "periods": latest.get("periods") or (config or {}).get("periods"),
        "active": latest.get("active"),
        "staleness_max": latest.get("staleness_max"),
        "p_min": latest.get("p_min"),
        "p_max": latest.get("p_max"),
        "refusals": latest.get("refusals"),
        "ticks": len(ticks),
        "active_series": series["active"][-24:],
        "staleness_series": series["staleness_max"][-24:],
    }


def _plane_block(prefix: str, plane_path: Optional[str]) -> Optional[dict]:
    """The in-band telemetry plane's trail as a report block: the
    newest observation's per-source merge metadata (version/age/hop,
    stale sources flagged against ``BLUEFOG_PLANE_MAX_AGE``) plus
    live-source and max-age series (the panel sparklines them) — None
    when no trail exists (a plane-free run stays noise-free)."""
    from ..observability.export import PLANE_SUFFIX, read_plane_trail
    path = plane_path or prefix + PLANE_SUFFIX
    config, records = read_plane_trail(path)
    if config is None and not records:
        return None
    obs = [r for r in records if r.get("kind") == "plane"]
    latest = obs[-1] if obs else {}
    sources = latest.get("sources") or []
    live_series, age_series = [], []
    for o in obs:
        srcs = o.get("sources") or []
        live_series.append(sum(1 for s in srcs if not s.get("stale")))
        ages = [s.get("age") for s in srcs
                if isinstance(s.get("age"), (int, float))]
        age_series.append(max(ages) if ages else 0)
    return {
        "path": path,
        "size": (config or {}).get("size"),
        "rank": (config or {}).get("rank"),
        "schema_version": (config or {}).get("schema_version"),
        "max_age": (config or {}).get("max_age"),
        "step": latest.get("step"),
        "observations": len(obs),
        "sources": sources,
        "live": sum(1 for s in sources if not s.get("stale")),
        "stale": sum(1 for s in sources if s.get("stale")),
        "live_series": live_series[-24:],
        "age_max_series": age_series[-24:],
    }


def _fleet_block(prefix: str, fleet_path: Optional[str]) -> Optional[dict]:
    """The fleet supervisor's trail as a report block: per-rank pid /
    last-heartbeat step / respawn count / last lifecycle event, the
    lifecycle-event tallies, and recent membership transitions — None
    when no trail exists (a single-process run stays noise-free)."""
    from ..observability.export import FLEET_SUFFIX, read_fleet_trail
    path = fleet_path or prefix + FLEET_SUFFIX
    config, records = read_fleet_trail(path)
    if config is None and not records:
        return None
    events = [r for r in records if r.get("kind") == "fleet_event"]
    size = (config or {}).get("size") or 0
    per_rank = {}
    counts = {}
    transitions = []
    done_rc = None
    for e in events:
        ev = e.get("event")
        counts[ev] = counts.get(ev, 0) + 1
        rank = e.get("rank")
        if ev == "done":
            done_rc = e.get("rc")
        if ev == "membership":
            transitions.append({"rank": rank, "step": e.get("step"),
                                "state": e.get("transition")})
            continue
        if rank is None:
            continue
        row = per_rank.setdefault(str(rank), {
            "pid": None, "last_heartbeat": None, "respawns": 0,
            "last_event": None, "rc": None, "alive": False})
        row["last_event"] = ev
        if e.get("pid") is not None:
            row["pid"] = e["pid"]
        if ev == "heartbeat" and e.get("step") is not None:
            row["last_heartbeat"] = e["step"]
        if ev in ("spawn", "respawn"):
            row["alive"] = True
            row["respawns"] = e.get("respawns", row["respawns"]) or 0
        elif ev == "exit":
            row["alive"] = False
            row["rc"] = e.get("rc")
    return {
        "path": path,
        "size": size,
        "respawn": (config or {}).get("respawn"),
        "max_respawns": (config or {}).get("max_respawns"),
        "per_rank": per_rank,
        "events": counts,
        "transitions": transitions[-12:],
        "alive": sum(1 for r in per_rank.values() if r["alive"]),
        "rc": done_rc,
    }


def render_fleet(block: dict, *, width: int = 12) -> str:
    """The fleet-supervisor panel (``--fleet``): per-process pid /
    last-heartbeat / respawn-count rows from the supervisor's trail,
    lifecycle-event tallies, and recent membership transitions."""
    counts = block.get("events") or {}
    lines = [f"fleet:  alive {block.get('alive', '-')}"
             f"/{block.get('size', '-')}  "
             f"respawn={'on' if block.get('respawn') else 'off'}  "
             f"spawns {counts.get('spawn', 0)}  "
             f"exits {counts.get('exit', 0)}  "
             f"respawns {counts.get('respawn', 0)}"
             + (f"  rc {block['rc']}" if block.get("rc") is not None
                else "")]
    for rank in sorted(block.get("per_rank") or {}, key=int):
        row = block["per_rank"][rank]
        tag = "up" if row.get("alive") else (
            f"rc {row.get('rc')}" if row.get("rc") is not None else "down")
        lines.append(
            f"  rank {rank:>3}  pid {str(row.get('pid', '-')):>7}  "
            f"hb {str(row.get('last_heartbeat', '-')):>6}  "
            f"respawns {row.get('respawns', 0)}  "
            f"last {str(row.get('last_event', '-')):<10} [{tag}]")
    if block.get("transitions"):
        lines.append("  membership:")
        for t in block["transitions"]:
            lines.append(f"    step {str(t.get('step', '-')):>5}  "
                         f"rank {str(t.get('rank', '-')):>3} -> "
                         f"{t.get('state', '-')}")
    return "\n".join(lines)


def render_plane(block: dict, *, width: int = 12) -> str:
    """The in-band telemetry plane panel (``--plane``): one rank's
    gossiped fleet view — live/stale source counts, the live-source and
    max-age sparklines, then per-source version/age/hop rows with stale
    sources (row older than ``BLUEFOG_PLANE_MAX_AGE`` steps) flagged."""
    lines = [f"plane (rank {block.get('rank', '-')} view):  "
             f"step {block.get('step', '-')}  "
             f"live {block.get('live', '-')}"
             f"/{block.get('size', '-')}  "
             f"stale {block.get('stale', 0)}  "
             f"max_age {block.get('max_age', '-')}"]
    live = [s for s in block.get("live_series", [])
            if isinstance(s, (int, float))]
    if live:
        lines.append(f"  live sources  {sparkline(live, width)}")
    ages = [s for s in block.get("age_max_series", [])
            if isinstance(s, (int, float))]
    if ages:
        lines.append(f"  age max       {sparkline(ages, width)}  "
                     f"last {ages[-1]:g}")
    for s in block.get("sources", []):
        tag = "STALE" if s.get("stale") else "ok"
        lines.append(
            f"  src {str(s.get('rank', '-')):>3}  "
            f"step {str(s.get('step', '-')):>5}  "
            f"v {str(s.get('version', '-')):>5}  "
            f"age {str(s.get('age', '-')):>3}  "
            f"hop {str(s.get('hop', '-')):>2}  [{tag}]")
    return "\n".join(lines)


def render_async(block: dict, *, width: int = 12) -> str:
    """The async-training panel (``--async``): cadence periods, the
    fired-ranks and effective-staleness sparklines against the
    ``BLUEFOG_ASYNC_MAX_STALENESS`` bound, push-sum P spread, and
    bounded-staleness refusal alerts."""
    periods = block.get("periods")
    lines = [f"async:  step {block.get('step', '-')}  "
             f"fired {block.get('active', '-')}"
             f"/{block.get('size', '-')}  "
             f"periods {periods if periods is not None else '-'}  "
             f"cap {block.get('max_staleness', '-')}"]
    act = [s for s in block.get("active_series", [])
           if isinstance(s, (int, float))]
    if act:
        lines.append(f"  fired ranks    {sparkline(act, width)}")
    stale = [s for s in block.get("staleness_series", [])
             if isinstance(s, (int, float))]
    if stale:
        bound = block.get("max_staleness")
        flag = (" ⚠ at bound" if bound is not None and stale
                and stale[-1] >= bound else "")
        lines.append(f"  staleness max  {sparkline(stale, width)}  "
                     f"last {stale[-1]:g}{flag}")
    if block.get("p_min") is not None and block.get("p_max") is not None:
        lines.append(f"  push-sum P in [{block['p_min']:.4f}, "
                     f"{block['p_max']:.4f}]")
    if block.get("refusals"):
        lines.append(f"  ⚠ staleness-cap refusals: {block['refusals']}")
    return "\n".join(lines)


def render_checkpoint(block: dict, *, width: int = 12) -> str:
    """Terminal panel for the checkpoint block: durability headline,
    save-time sparkline, and protocol-event alerts."""
    lines = [f"checkpoint  dir={block.get('dir')}  "
             f"every={block.get('every')}  keep={block.get('keep')}  "
             f"replicas={block.get('replicas')}"]
    spark = sparkline(block.get("save_s_series") or [], width=width)
    lines.append(
        f"  durable step {block.get('last_durable_step')}  "
        f"saves {block.get('saves')}  "
        f"last {_fmt(block.get('save_s'), 's')} / "
        f"{_fmt(block.get('bytes'), 'B')}  {spark}")
    alerts = []
    if block.get("torn_shards"):
        alerts.append(f"torn shards: {block['torn_shards']}")
    if block.get("replica_repairs"):
        alerts.append(f"replica repairs: {block['replica_repairs']}")
    if block.get("restores"):
        alerts.append(f"restores: {block['restores']}")
    if block.get("skipped"):
        alerts.append(f"skipped saves: {block['skipped']}")
    if alerts:
        lines.append("  ⚠ " + "; ".join(alerts))
    for e in (block.get("events") or {}).get("recent", []):
        lines.append(f"    step {e.get('step')}: {e.get('event')}"
                     + (f" ({e.get('detail')})" if e.get("detail")
                        else ""))
    return "\n".join(lines)


def render_membership(block: dict, *, width: int = 12) -> str:
    """The elastic-membership panel (``--membership``): fleet-size
    sparkline (active ranks over time), capacity usage, the latest
    per-rank states, and recent join/leave transitions."""
    cap = block.get("capacity") or []
    lines = [f"membership:  step {block.get('step', '-')}  "
             f"active {block.get('active', '-')}"
             f"/{block.get('size', '-')}  "
             f"syncing {block.get('syncing', '-')}  "
             f"capacity {len(cap)} slot{'s' if len(cap) != 1 else ''}"]
    series = [s for s in block.get("active_series", [])
              if isinstance(s, (int, float))]
    if series:
        lines.append(f"  active ranks {sparkline(series, width)}")
    states = block.get("states") or {}
    off = {r: s for r, s in states.items() if s != "active"}
    if off:
        lines.append("  non-active: " + ", ".join(
            f"{r}={s}" for r, s in sorted(
                off.items(), key=lambda kv: (0, int(kv[0]))
                if kv[0].isdigit() else (1, kv[0]))))
    ev = block.get("events") or {}
    if ev.get("total"):
        lines.append(f"  transitions: {ev['total']}")
        for e in ev.get("recent", []):
            lines.append(
                f"    step {str(e.get('step', '-')):>5}  rank "
                f"{e.get('rank')} -> {e.get('transition')}")
    return "\n".join(lines)


def render_serving(block: dict, *, width: int = 12) -> str:
    """The serving panel (``--serving``): per-replica staleness
    sparklines against the bound, router hit counts, failover alerts."""
    bound = block.get("max_staleness")
    lines = [f"serving ({block.get('window') or '-'}):  "
             f"step {block.get('step', '-')}  "
             f"{_fmt(block.get('requests_per_s'))} req/s  "
             f"bound {bound if bound is not None else '-'} steps  "
             f"refused {block.get('refused', 0)}"]
    hits = block.get("hits") or {}
    for rep in block.get("replicas", []):
        st = block.get("staleness", {}).get(rep, {})
        series = [s for s in st.get("series", [])
                  if isinstance(s, (int, float))]
        last = st.get("last")
        over = (bound is not None and isinstance(last, (int, float))
                and (last > bound or last < 0))
        tag = "STALE" if over else (
            "serving" if str(block.get("current")) == rep else "-")
        lines.append(
            f"  replica {rep:>3}  stale {_fmt(float(last)) if isinstance(last, (int, float)) else '-':>6} "
            f"{sparkline(series, width):<{width}} "
            f"hits {hits.get(rep, 0):>6}  [{tag}]")
    fo = block.get("failovers") or {}
    if fo.get("total"):
        lines.append(f"  failovers: {fo['total']}")
        for ev in fo.get("recent", []):
            lines.append(
                f"    step {str(ev.get('step', '-')):>5}  "
                f"{ev.get('replica_from')} -> {ev.get('replica_to')}  "
                f"({ev.get('reason')})")
    return "\n".join(lines)


def render_edge_heatmap(edges: dict, *, top: int = 0) -> str:
    """Terminal heatmap of the measured edge cost matrix (``--edges``):
    one cell per (src row, dst column), shaded by one-way latency
    normalized across the matrix (``.`` = no edge), with the slowest
    edges listed under it.  ``edges`` is the ``latest_edges()`` dict."""
    from ..observability.commprof import EdgeCostMatrix
    entries = edges["entries"]
    ranks = sorted({e["src"] for e in entries}
                   | {e["dst"] for e in entries})
    m = EdgeCostMatrix(n=(max(ranks) + 1 if ranks else 0),
                       entries=entries)
    lat = {(s, d): m.latency_us(s, d) for s, d in m.edges()}
    finite = [v for v in lat.values() if v is not None and v > 0]
    lines = [f"edge latency heatmap (probed at step {edges.get('step')}, "
             f"one-way µs at the largest payload):"]
    if not finite:
        return "\n".join(lines + ["  (no finite edge measurements)"])
    lo, hi = min(finite), max(finite)
    span = (hi - lo) or 1.0
    # 3-char column stride shared by the header and every row, so each
    # dst label sits exactly over its cells
    header = "      " + "".join(f"{d:>3}" for d in ranks)
    lines.append(header + "   <- dst")
    for s in ranks:
        row = []
        for d in ranks:
            v = lat.get((s, d))
            if v is None:
                row.append(f"{'.':>3}")
            else:
                tick = _TICKS[min(len(_TICKS) - 1,
                                  int((v - lo) / span * len(_TICKS)))]
                row.append(f"{tick:>3}")
        lines.append(f"  {s:>2} |" + "".join(row))
    worst = sorted(lat.items(), key=lambda kv: -(kv[1] or 0))
    for (s, d), v in worst[:max(3, top)]:
        lines.append(f"  slow: {s}->{d}  {_fmt(v)}µs")
    return "\n".join(lines)


def render_decisions(block: dict, *, limit: int = 6) -> str:
    """The controller decisions panel: the newest trail entries, one
    line each — shadow entries marked ``would`` (logged, not actuated)."""
    lines = [f"decisions ({block['total']} total, "
             f"mode {block.get('mode') or '-'}):"]
    for d in block.get("recent", [])[-limit:]:
        tag = "applied" if d.get("applied") else (
            "would" if d.get("mode") == "shadow" else "skipped")
        # str() everything: the reader is tolerant by contract, so a
        # malformed record must render as '-', never crash the frame
        lines.append(
            f"  step {str(d.get('step', '-')):>5}  "
            f"{d.get('knob')}:{d.get('action')}"
            f" -> {d.get('value')}  [{d.get('rule')}] ({tag})")
    return "\n".join(lines)


def render_dashboard(view, report, *, width: int = 12) -> str:
    """The human frame: header, per-rank sparkline table, alerts."""
    lines = []
    last = view.last_step()
    stamp = time.strftime("%H:%M:%S")
    status = ("OK" if report.ok
              else f"{len(report.alerts)} ALERT"
                   f"{'S' if len(report.alerts) != 1 else ''}")
    lines.append(
        f"bfmonitor  {stamp}  fleet: {len(view.ranks)} rank(s)"
        + (f" (expected {view.expected_ranks})"
           if view.expected_ranks
           and view.expected_ranks != len(view.ranks) else "")
        + f"  step: {'-' if last is None else last}"
          f"  window: {report.step_lo}..{report.step_hi}  [{status}]")
    dead = {v.rank for v in report.verdicts
            if v.rule in ("dead_rank", "rank_silent")
            and v.rank is not None}
    if dead:
        lines.append(f"degraded/dead ranks: "
                     f"{', '.join(str(r) for r in sorted(dead))}")
    if last is not None:
        st = view.fleet_spread(last, "consensus_dist",
                               exclude=H.UNMEASURED)
        if st is not None:
            lines.append(
                f"consensus@{last}:  min {_fmt(st.min)}  p50 "
                f"{_fmt(st.p50)}  p95 {_fmt(st.p95)}  max {_fmt(st.max)}")
    lines.append("")
    lines.append(f"{'rank':>4} {'steps':>5} {'consensus':>10} "
                 f"{'trend':<{width}} {'step':>8} {'trend':<{width}}  flags")
    flagged = {}
    for v in report.alerts:
        if v.rank is not None:
            flagged.setdefault(v.rank, []).append(v.rule)
    for rank in view.ranks:
        cd = [x for _, x in view.series_of(rank, "consensus_dist")
              if x != H.UNMEASURED]
        wall = [w for _, w in view.step_wall_s(rank)]
        nsteps = len(view.per_rank.get(rank, {}))
        lines.append(
            f"{rank:>4} {nsteps:>5} "
            f"{_fmt(cd[-1] if cd else None):>10} "
            f"{sparkline(cd, width, log_scale=True):<{width}} "
            f"{_fmt(wall[-1] if wall else None, 'ms'):>8} "
            f"{sparkline(wall, width):<{width}}  "
            f"{','.join(flagged.get(rank, [])) or '-'}")
    if report.verdicts:
        lines.append("")
        lines.append("verdicts:")
        for v in report.verdicts:
            lines.append(f"  [{_SEV_TAG.get(v.severity, v.severity)}] "
                         f"{v.rule}: {v.message}")
    return "\n".join(lines)


_FAIL_LEVELS = {"never": (), "critical": ("critical",),
                "warn": ("warn", "critical")}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bfmonitor",
        description="live fleet health dashboard over BLUEFOG_METRICS "
                    "JSONL series (docs/observability.md)")
    p.add_argument("prefix",
                   help="metrics prefix: tails <prefix><rank>.jsonl")
    p.add_argument("--once", action="store_true",
                   help="render one frame / report and exit")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable report instead of the "
                        "dashboard (CI gating)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="live refresh seconds (default 2)")
    p.add_argument("--window", type=int, default=None,
                   help="health window in steps (default "
                        "BLUEFOG_HEALTH_WINDOW or 8)")
    p.add_argument("--ranks", type=int, default=None,
                   help="expected fleet size: silent ranks become "
                        "rank_silent verdicts")
    p.add_argument("--verdicts", default=None, metavar="PATH",
                   help="append HealthReports to this verdict JSONL "
                        "(the controller feed)")
    p.add_argument("--decisions", default=None, metavar="PATH",
                   help="controller decision trail to render (default: "
                        "<prefix>decisions.jsonl when it exists)")
    p.add_argument("--edges", action="store_true",
                   help="render the measured edge-cost heatmap (the comm "
                        "profiler's newest 'edges' record) under the "
                        "dashboard")
    p.add_argument("--serving", action="store_true",
                   help="render the serving panel (replica staleness "
                        "sparklines, router hit counts, failover alerts) "
                        "from the <prefix>serving.jsonl trail")
    p.add_argument("--serving-trail", default=None, metavar="PATH",
                   help="serving trail to render (default: "
                        "<prefix>serving.jsonl when it exists)")
    p.add_argument("--membership", action="store_true",
                   help="render the elastic-membership panel (fleet-size "
                        "sparkline, per-rank states, join/leave "
                        "transitions) from the <prefix>membership.jsonl "
                        "trail")
    p.add_argument("--membership-trail", default=None, metavar="PATH",
                   help="membership trail to render (default: "
                        "<prefix>membership.jsonl when it exists)")
    p.add_argument("--checkpoint", action="store_true",
                   help="render the durable-fleet-state panel (last "
                        "durable step, save-time sparkline, torn-shard/"
                        "replica-repair alerts) from the "
                        "<prefix>ckpt.jsonl trail")
    p.add_argument("--checkpoint-trail", default=None, metavar="PATH",
                   help="checkpoint trail to render (default: "
                        "<prefix>ckpt.jsonl when it exists)")
    p.add_argument("--async", dest="async_panel", action="store_true",
                   help="render the asynchronous-training panel (cadence "
                        "periods, fired-rank and staleness sparklines, "
                        "push-sum P spread, bounded-staleness refusal "
                        "alerts) from the <prefix>async.jsonl trail")
    p.add_argument("--async-trail", default=None, metavar="PATH",
                   help="async trail to render (default: "
                        "<prefix>async.jsonl when it exists)")
    p.add_argument("--plane", dest="plane_panel", action="store_true",
                   help="render the in-band telemetry plane panel (one "
                        "rank's gossiped fleet view: per-source "
                        "version/age/hop, stale sources flagged against "
                        "BLUEFOG_PLANE_MAX_AGE) from the "
                        "<prefix>plane.jsonl trail")
    p.add_argument("--plane-trail", default=None, metavar="PATH",
                   help="plane trail to render (default: "
                        "<prefix>plane.jsonl when it exists)")
    p.add_argument("--fleet", dest="fleet_panel", action="store_true",
                   help="render the fleet-supervisor panel (per-process "
                        "pid/rank/last-heartbeat/respawn-count, "
                        "lifecycle events, membership transitions) from "
                        "the <prefix>fleet.jsonl trail")
    p.add_argument("--fleet-trail", default=None, metavar="PATH",
                   help="fleet trail to render (default: "
                        "<prefix>fleet.jsonl when it exists)")
    p.add_argument("--fail-on", choices=sorted(_FAIL_LEVELS),
                   default="never",
                   help="with --once: exit 1 when a verdict at or above "
                        "this severity is active")
    args = p.parse_args(argv)

    # one cache across live frames: each refresh parses only the bytes
    # the fleet appended since the previous one
    cache = AG.TailCache()

    def frame():
        view, report, out = build_report(
            args.prefix, window=args.window, expected_ranks=args.ranks,
            verdicts_path=args.verdicts, decisions_path=args.decisions,
            serving_path=args.serving_trail,
            membership_path=args.membership_trail,
            checkpoint_path=args.checkpoint_trail,
            async_path=args.async_trail,
            plane_path=args.plane_trail,
            fleet_path=args.fleet_trail, cache=cache)
        if args.json:
            print(json.dumps(out))
        else:
            print(render_dashboard(view, report))
            if out.get("decisions"):
                print()
                print(render_decisions(out["decisions"]))
            if args.membership:
                if out.get("membership"):
                    print()
                    print(render_membership(out["membership"]))
                else:
                    print("\n(no membership trail yet — elastic runs "
                          "write <prefix>membership.jsonl; see "
                          "docs/resilience.md)")
            if args.serving:
                if out.get("serving"):
                    print()
                    print(render_serving(out["serving"]))
                else:
                    print("\n(no serving trail yet — the router writes "
                          "<prefix>serving.jsonl; see docs/serving.md)")
            if args.checkpoint:
                if out.get("checkpoint"):
                    print()
                    print(render_checkpoint(out["checkpoint"]))
                else:
                    print("\n(no checkpoint trail yet — the "
                          "FleetCheckpointer writes <prefix>ckpt.jsonl; "
                          "see docs/checkpoint.md)")
            if args.async_panel:
                if out.get("async"):
                    print()
                    print(render_async(out["async"]))
                else:
                    print("\n(no async trail yet — asynchronous runs "
                          "write <prefix>async.jsonl; see "
                          "docs/async.md)")
            if args.plane_panel:
                if out.get("plane"):
                    print()
                    print(render_plane(out["plane"]))
                else:
                    print("\n(no plane trail yet — attach a PlaneTrail "
                          "to the TelemetryPlane; it writes "
                          "<prefix>plane.jsonl; see "
                          "docs/observability.md)")
            if args.fleet_panel:
                if out.get("fleet"):
                    print()
                    print(render_fleet(out["fleet"]))
                else:
                    print("\n(no fleet trail yet — the bfrun --fleet "
                          "supervisor writes <prefix>fleet.jsonl; see "
                          "docs/running.md)")
            if args.edges:
                edges = out.get("edges")
                if edges:
                    print()
                    print(render_edge_heatmap(edges))
                else:
                    print("\n(no edge matrix in the series yet — run the "
                          "probe: bench.py --profile-edges)")
        return report

    if args.once:
        report = frame()
        bad = [v for v in report.verdicts
               if v.severity in _FAIL_LEVELS[args.fail_on]]
        return 1 if bad else 0
    try:
        while True:
            if not args.json:
                # clear + home, like watch(1); plain frames in json mode
                sys.stdout.write("\x1b[2J\x1b[H")
            frame()
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
