"""``bfrun`` — launch a bluefog_tpu program (reference: ``run/run.py:121-203``).

The reference execve's ``mpirun`` to spawn -np ranks.  A JAX program is
single-controller SPMD — one process drives every local device — so:

* **Single host**: ``bfrun -np 8 python train.py`` runs the command in-place
  with the device view configured: on real TPU hardware the 8 chips are
  discovered by the runtime; with ``--platform cpu`` an 8-device virtual
  host platform is forced via XLA flags — the TPU analog of the reference's
  localhost oversubscription (Makefile:5-8).
* **Multi host**: ``bfrun -np 16 -H host1:8,host2:8 python train.py`` starts
  one controller per host over ssh, wiring ``jax.distributed`` coordinator
  env vars (BLUEFOG_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID) that
  ``bf.init()`` consumes; collectives then ride ICI within a host and DCN
  across hosts.
"""

import argparse
import os
import shlex
import signal
import subprocess
import sys
from typing import List, Tuple

from . import env_util, network_util

_FORWARD_PREFIXES = ["BLUEFOG_", "JAX_", "XLA_", "LIBTPU_", "TPU_",
                     "PYTHONPATH"]


def parse_args(argv: List[str]) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="bfrun", description="BlueFog-TPU launcher",
        usage="bfrun [-np N] [-H hosts | --hostfile F] [options] command ...")
    parser.add_argument("-v", "--version", action="store_true")
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="total number of devices (single host) or "
                             "must equal the sum of host slots (multi host)")
    parser.add_argument("-H", "--hosts", default=None,
                        help="comma-separated host:slots list")
    parser.add_argument("--hostfile", default=None,
                        help="file with 'hostname slots=N' lines")
    parser.add_argument("-p", "--ssh-port", type=int, default=None)
    parser.add_argument("--platform", default=None,
                        choices=["tpu", "cpu"],
                        help="force a JAX platform (cpu => -np virtual "
                             "host devices, like the reference's localhost "
                             "oversubscription)")
    parser.add_argument("--coordinator-port", type=int, default=3389,
                        help="port for the jax.distributed coordinator "
                             "(multi-host only)")
    parser.add_argument("--network-interface", default=None,
                        help="NIC for coordinator/DCN traffic (reference "
                             "--network-interface, run.py:84-118): the "
                             "coordinator advertises this interface's IPv4 "
                             "when it launches here, process 0 binds to it "
                             "(BLUEFOG_NETWORK_INTERFACE is exported to "
                             "every worker and consumed by bf.init)")
    parser.add_argument("--fleet", type=int, default=None,
                        help="run as a local fleet supervisor: spawn N "
                             "worker OS processes with per-process env "
                             "(fleet rank, peer map, metrics prefix), "
                             "monitor heartbeats + waitpid, drive "
                             "elastic membership from real process "
                             "lifecycle, fan out SIGTERM, aggregate "
                             "exit codes (docs/running.md 'Fleet mode')")
    parser.add_argument("--respawn", action="store_true",
                        help="with --fleet: relaunch a replacement for "
                             "a crashed worker; it re-admits through "
                             "the announce->sync->activate membership "
                             "protocol")
    parser.add_argument("--max-respawns", type=int, default=1,
                        help="with --fleet --respawn: relaunch budget "
                             "per rank (default 1)")
    parser.add_argument("--fleet-trail", default=None,
                        help="with --fleet: fleet.jsonl trail path for "
                             "the supervisor's lifecycle events "
                             "(default: BLUEFOG_METRICS prefix + "
                             "fleet.jsonl, else ./fleet.jsonl)")
    parser.add_argument("--timeline-filename", default=None,
                        help="per-rank chrome-tracing output prefix "
                             "(exports BLUEFOG_TIMELINE)")
    parser.add_argument("--nodes-per-machine", type=int, default=None,
                        help="simulate multi-machine hierarchy on one host "
                             "(exports BLUEFOG_NODES_PER_MACHINE)")
    # MPI-era flags the reference launcher accepts (run.py:88-97) — taken
    # for drop-in compatibility with existing bfrun scripts, with honest
    # TPU-native semantics instead of silent drops:
    parser.add_argument("--use-infiniband", action="store_true",
                        help="accepted for reference compatibility; the "
                             "TPU transport (ICI/DCN) is selected by "
                             "XLA/jax.distributed, so this is a no-op "
                             "(a note is printed)")
    parser.add_argument("--extra-mpi-flags", default=None,
                        help="accepted for reference compatibility; there "
                             "is no mpirun underneath — use KEY=VAL "
                             "entries and they are exported to every "
                             "worker's environment instead (anything else "
                             "is rejected)")
    parser.add_argument("--prefix", default=None,
                        help="accepted for reference compatibility (MPI "
                             "install prefix); unused here (a note is "
                             "printed)")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def _resolve_hosts(args) -> List[Tuple[str, int]]:
    if args.hosts and args.hostfile:
        raise SystemExit("bfrun: use either -H or --hostfile, not both")
    if args.hostfile:
        return network_util.parse_hostfile(args.hostfile)
    if args.hosts:
        return network_util.parse_host_spec(args.hosts)
    return []


def compat_flag_env(args, prog: str = None) -> dict:
    """Handle the MPI-era compat flags ONCE per invocation: print each
    no-op note a single time, validate --extra-mpi-flags before any
    per-host work, and return the KEY=VAL env additions (the `mpirun -x`
    role).  Memoized on the args namespace — multi-host paths call the
    per-host env builder N times and must not repeat the notes."""
    cached = getattr(args, "_compat_env", None)
    if cached is not None:
        return cached
    prog = prog or getattr(args, "_prog", "bfrun")
    extra = {}
    if getattr(args, "use_infiniband", False):
        print(f"{prog}: --use-infiniband is a no-op on TPU (ICI/DCN "
              f"transport is selected by XLA/jax.distributed)",
              file=sys.stderr)
    if getattr(args, "prefix", None):
        print(f"{prog}: --prefix {args.prefix} is unused on TPU (no MPI "
              f"installation underneath)", file=sys.stderr)
    if getattr(args, "ipython_profile", None):
        print(f"{prog}: --ipython-profile {args.ipython_profile} is "
              f"unused (this cluster is not ipyparallel-based)",
              file=sys.stderr)
    if getattr(args, "extra_mpi_flags", None):
        # the one honest mapping: env assignments ride to every worker
        # exactly like mpirun -x; raw mpirun switches have no target
        import re as _re
        for tok in args.extra_mpi_flags.split():
            if "=" in tok and not tok.startswith("-"):
                key, _, val = tok.partition("=")
                if not _re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", key):
                    # emitted unquoted as KEY=... in the remote ssh line:
                    # a non-identifier would be parsed as shell syntax
                    raise SystemExit(
                        f"{prog}: --extra-mpi-flags key {key!r} is not a "
                        f"valid environment variable name")
                extra[key] = val
            else:
                raise SystemExit(
                    f"{prog}: --extra-mpi-flags entry {tok!r} has no "
                    f"TPU-side meaning (no mpirun underneath); only "
                    f"KEY=VAL env entries are supported")
    args._compat_env = extra
    return extra


def _apply_common_flags(args, env: dict, local_slots: int) -> dict:
    """Flag → env translation shared by the single- and multi-host paths
    (reference composes mpirun's -x list the same way, run.py:186-198)."""
    env.update(compat_flag_env(args))
    if args.timeline_filename:
        env["BLUEFOG_TIMELINE"] = args.timeline_filename
    if args.nodes_per_machine:
        env["BLUEFOG_NODES_PER_MACHINE"] = str(args.nodes_per_machine)
    if getattr(args, "network_interface", None):
        # each worker resolves the iface on ITS OWN machine at bf.init()
        # time (context._maybe_init_jax_distributed) — the launcher cannot
        # know a remote coordinator's addresses
        env["BLUEFOG_NETWORK_INTERFACE"] = args.network_interface
    if args.platform == "cpu":
        if local_slots:
            env_util.force_virtual_cpu_devices(env, local_slots)
        else:
            env["JAX_PLATFORMS"] = "cpu"
    elif args.platform:
        env["JAX_PLATFORMS"] = args.platform
    # async-collective / latency-hiding scheduler flags, probe-gated
    # against the installed XLA build (skip with BLUEFOG_LATENCY_HIDING=0
    # / BLUEFOG_NO_XLA_FLAG_INJECT).  CPU targets skip them — whether
    # forced by --platform cpu or by an inherited JAX_PLATFORMS=cpu:
    # XLA:CPU keeps collectives synchronous anyway and the virtual-device
    # runs value deterministic scheduling.
    platform_hint = (args.platform or env.get("JAX_PLATFORMS", "")).lower()
    if "cpu" not in platform_hint:
        env_util.latency_hiding_flags(env)
    return env


def make_single_host_env(args, base_env=None) -> dict:
    env = dict(os.environ if base_env is None else base_env)
    _apply_common_flags(args, env, args.num_proc)
    if args.num_proc:
        env["BLUEFOG_EXPECTED_SIZE"] = str(args.num_proc)
    return env


def _launch_single_host(args) -> int:
    env = make_single_host_env(args)
    cmd = args.command
    os.execvpe(cmd[0], cmd, env)  # no return


def _launch_multi_host(args, hosts) -> int:
    total = sum(s for _, s in hosts)
    if args.num_proc and args.num_proc != total:
        raise SystemExit(
            f"bfrun: -np {args.num_proc} != sum of host slots {total}")
    # The coordinator address is dialed by every host — local-vs-remote
    # and NIC-pinning cases live in network_util.resolve_coordinator_host
    # (shared with ibfrun; reference --network-interface semantics)
    any_remote = any(not network_util.is_local_host(h) for h, _ in hosts)
    try:
        coord_host = network_util.resolve_coordinator_host(
            hosts[0][0], args.network_interface, args.ssh_port, any_remote)
    except ValueError as e:
        raise SystemExit(f"bfrun: {e}")
    coordinator = f"{coord_host}:{args.coordinator_port}"

    for host, _ in hosts:
        if not network_util.is_local_host(host):
            if not network_util.check_ssh(host, args.ssh_port):
                raise SystemExit(f"bfrun: ssh to {host} failed (reference "
                                 f"behavior run.py:134: abort early)")

    base_env = env_util.exportable_env()

    procs = []
    cwd = os.getcwd()
    for pid, (host, slots) in enumerate(hosts):
        env = _apply_common_flags(args, dict(base_env), slots)
        env.update({
            "BLUEFOG_COORDINATOR": coordinator,
            "BLUEFOG_NUM_PROCESSES": str(len(hosts)),
            "BLUEFOG_PROCESS_ID": str(pid),
        })
        if network_util.is_local_host(host):
            procs.append(subprocess.Popen(args.command, env={**os.environ, **env}))
        else:
            assigns = env_util.env_assignments(
                env, _FORWARD_PREFIXES, extra_keys=compat_flag_env(args))
            remote = (f"cd {shlex.quote(cwd)} && "
                      + " ".join(assigns) + " "
                      + " ".join(shlex.quote(c) for c in args.command))
            ssh = ["ssh", "-o", "BatchMode=yes"]
            if args.ssh_port:
                ssh += ["-p", str(args.ssh_port)]
            procs.append(subprocess.Popen(ssh + [host, remote]))

    def _terminate(*_):
        for p in procs:
            if p.poll() is None:
                p.terminate()
    signal.signal(signal.SIGINT, _terminate)
    signal.signal(signal.SIGTERM, _terminate)

    # Poll all workers so one crashed host tears the job down immediately —
    # a sequential wait() would hang on an earlier-listed host stuck in a
    # collective waiting for the dead one.
    import time
    rc = 0
    pending = set(procs)
    while pending:
        for p in list(pending):
            p_rc = p.poll()
            if p_rc is None:
                continue
            pending.discard(p)
            if p_rc != 0 and rc == 0:
                rc = p_rc
                _terminate()
        if pending:
            time.sleep(0.2)
    return rc


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    if args.version:
        from ..version import __version__
        print(f"bfrun (bluefog_tpu) {__version__}")
        return 0
    if not args.command:
        raise SystemExit("bfrun: no command given (try: bfrun -np 8 "
                         "python train.py)")
    if args.command[0] == "--":
        args.command = args.command[1:]
    if args.fleet:
        if args.hosts or args.hostfile:
            raise SystemExit("bfrun: --fleet supervises local OS "
                             "processes; use -H/--hostfile without it "
                             "for the multi-host path")
        from ..fleet.supervisor import run_fleet
        return run_fleet(args)
    hosts = _resolve_hosts(args)
    # A single *remote* host still needs the ssh + coordinator path; only a
    # bare or single-local-host spec runs in place.
    if len(hosts) > 1 or (
            hosts and not network_util.is_local_host(hosts[0][0])):
        return _launch_multi_host(args, hosts)
    if hosts and args.num_proc is None:
        args.num_proc = hosts[0][1]  # -H localhost:4 without -np
    return _launch_single_host(args)


if __name__ == "__main__":
    sys.exit(main())
