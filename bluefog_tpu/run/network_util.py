"""Host parsing and reachability helpers (reference: ``run/network_util.py``)."""

import subprocess
from typing import List, Optional, Tuple


def parse_host_spec(spec: str) -> List[Tuple[str, int]]:
    """``"h1:8,h2:8"`` → ``[("h1", 8), ("h2", 8)]``; slot defaults to 1
    (reference -H format, run/run.py:64-70)."""
    hosts = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            hosts.append((name, int(slots)))
        else:
            hosts.append((part, 1))
    return hosts


def parse_hostfile(path: str) -> List[Tuple[str, int]]:
    """Hostfile lines ``hostname slots=N`` (reference --hostfile,
    run/run.py:71-77)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            fields = line.split()
            name = fields[0]
            slots = 1
            for field in fields[1:]:
                if field.startswith("slots="):
                    slots = int(field.split("=", 1)[1])
            hosts.append((name, slots))
    return hosts


def interface_address(iface: str) -> str:
    """IPv4 address bound to ``iface`` (Linux SIOCGIFADDR).

    The TPU-native analog of the reference pinning NCCL/gloo sockets to a
    NIC (``run/run.py:84-118``, ``--network-interface`` → iface env pins):
    DCN-facing multi-host jobs choose which interface the jax.distributed
    coordinator binds and advertises instead of trusting hostname
    resolution to pick the right network."""
    import fcntl
    import socket
    import struct
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        packed = struct.pack("256s", iface.encode()[:255])
        try:
            addr = fcntl.ioctl(s.fileno(), 0x8915, packed)[20:24]  # SIOCGIFADDR
        except OSError as e:
            # ValueError, not SystemExit: this also runs inside bf.init()
            # on the coordinator host (context._maybe_init_jax_distributed),
            # where a launcher-style exit would bury the diagnostic; bfrun
            # converts it at its own call site
            raise ValueError(
                f"cannot resolve an IPv4 address on interface "
                f"{iface!r}: {e}")
        return socket.inet_ntoa(addr)
    finally:
        s.close()


def remote_interface_address(host: str, iface: str,
                             ssh_port: Optional[int] = None,
                             timeout: int = 15) -> str:
    """Resolve ``iface``'s IPv4 on a REMOTE host over ssh.

    Used by bfrun when the coordinator host is not the launch host: the
    advertised BLUEFOG_COORDINATOR must carry the address process 0 will
    actually bind (context.py pins ``coordinator_bind_address`` to this
    same iface on that machine), not whatever the hostfile name happens
    to resolve to — hostname misresolution onto the wrong NIC is exactly
    what ``--network-interface`` exists to fix, and with a remote
    coordinator the launcher cannot resolve the iface locally.  Runs the
    same SIOCGIFADDR lookup as :func:`interface_address` via a
    stdlib-only snippet.  Raises ValueError with the remote diagnostic on
    failure (bfrun converts to SystemExit at its call site)."""
    import re
    if not re.fullmatch(r"[\w.:-]+", iface):
        raise ValueError(f"invalid interface name {iface!r}")
    snippet = ("import socket,struct,fcntl;"
               "s=socket.socket(socket.AF_INET,socket.SOCK_DGRAM);"
               "print(socket.inet_ntoa(fcntl.ioctl(s.fileno(),0x8915,"
               f"struct.pack('256s',{iface.encode()!r}))[20:24]))")
    cmd = ["ssh", "-o", "BatchMode=yes"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    cmd += [host, f'python3 -c "{snippet}"']
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        raise ValueError(
            f"ssh to {host} timed out resolving interface {iface!r}")
    except FileNotFoundError:
        raise ValueError("ssh not found on this machine")
    if out.returncode != 0 or not out.stdout.strip():
        raise ValueError(
            f"cannot resolve interface {iface!r} on {host}: "
            f"{(out.stderr or out.stdout).strip() or 'no output'}")
    addr = out.stdout.strip().splitlines()[-1].strip()
    import socket
    try:
        socket.inet_aton(addr)
    except OSError:
        raise ValueError(
            f"unexpected address {addr!r} from {host} for {iface!r}")
    return addr


def resolve_coordinator_host(coord_host: str, iface: Optional[str],
                             ssh_port: Optional[int],
                             any_remote: bool) -> str:
    """The address every worker should dial for the jax.distributed
    coordinator (shared by bfrun and ibfrun).

    * local coordinator + pinned iface → that iface's IPv4 (process 0
      binds it);
    * local coordinator + remote workers → this machine's routable fqdn
      (a loopback name would point remote workers at themselves);
    * REMOTE coordinator + pinned iface → the iface's IPv4 resolved over
      ssh ON that host — advertising the hostfile name while process 0
      binds the iface IP (context.py's ``coordinator_bind_address``)
      would send workers to whatever the name resolves to, possibly a
      NIC nothing listens on, the exact misresolution
      ``--network-interface`` exists to fix;
    * otherwise the hostfile name unchanged.

    Raises ValueError on iface-resolution failure; launchers convert it
    to SystemExit under their own prog prefix."""
    if is_local_host(coord_host):
        if iface:
            return interface_address(iface)
        if any_remote:
            import socket
            return socket.getfqdn()
        return coord_host
    if iface:
        return remote_interface_address(coord_host, iface, ssh_port)
    return coord_host


_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def is_local_host(name: str) -> bool:
    if name in _LOCAL_NAMES:
        return True
    import socket
    try:
        return name in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


def check_ssh(host: str, ssh_port: Optional[int] = None,
              timeout: int = 10) -> bool:
    """Non-interactive ssh reachability probe (reference run.py:134)."""
    cmd = ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=no",
           "-o", f"ConnectTimeout={timeout}"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    cmd += [host, "true"]
    try:
        return subprocess.run(cmd, capture_output=True,
                              timeout=timeout + 5).returncode == 0
    except (subprocess.TimeoutExpired, FileNotFoundError):
        return False
