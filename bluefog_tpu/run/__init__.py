"""Launchers: ``bfrun`` (batch) and ``ibfrun`` (interactive).

TPU-native re-design of the reference launcher stack (``bluefog/run/`` —
``bfrun`` wraps mpirun at run.py:121-203, ``ibfrun`` wraps ipyparallel).
There is no mpirun here: a JAX program is single-controller SPMD, so
launching means (a) configuring the device view for one process on a single
host, or (b) starting one controller process per host wired together with
``jax.distributed`` over DCN.
"""
