"""``bfctl`` — dry-run / replay the closed-loop controller against
recorded telemetry.

The controller's whole trust story is that its decisions are a
DETERMINISTIC function of the recorded JSONL series (docs/control.md):
``bfctl replay`` proves it by re-running the sensing -> policy pipeline
over a finished run's ``<prefix><rank>.jsonl`` files and reproducing the
decision trail the live controller wrote — byte-for-byte on the decision
signatures (step, knob, action, value, rule).

Modes::

    bfctl replay /tmp/series_                    # print the trail JSON
    bfctl replay /tmp/series_ --out /tmp/d.jsonl # write a trail file
    bfctl replay /tmp/series_ --expect /tmp/series_decisions.jsonl
                                                 # exit 1 unless the live
                                                 # trail is reproduced
    bfctl show /tmp/series_decisions.jsonl       # pretty-print a trail
    bfctl show --schedule sched.json --edges e.json
                                                 # render a synthesized
                                                 # schedule's rounds +
                                                 # predicted costs

Replay semantics mirror the live hook exactly: the controller evaluates
inside ``opt.step(t)`` — before the caller logs step t — so an
evaluation at step t sees records ``<= t-1``; replay applies the same
cutoff.  The engine is re-instantiated from the trail's
``control_config`` head record (modes, initial mode, γ knob, config,
probe platform, cadence) so a replay needs no knowledge of the original
launch script; CLI flags override for dry-running hypothetical configs
against real telemetry.

Host-side only: no mesh, no device init, no tracing — a laptop can
replay a pod's trail.
"""

import argparse
import json
import sys
from typing import List, Optional

from ..control import policy as CTL
from ..observability import aggregate as AG
from ..observability import health as H

__all__ = ["main", "replay"]


def _truncated_view(view: AG.FleetView, cutoff: int) -> AG.FleetView:
    """The fleet view as the live controller saw it at an evaluation
    with records ``<= cutoff`` (loader gaps dropped — they are live-tail
    artifacts, and no decision rule consumes them)."""
    series = []
    for rank, s in sorted(view.series.items()):
        recs = [r for r in s.records
                if (st := AG._step_of(r)) is not None and st <= cutoff]
        series.append(AG.RankSeries(rank=rank, records=recs, path=s.path))
    return AG.FleetView(series, [], expected_ranks=view.expected_ranks)


def _engine_from(head: Optional[dict], args) -> CTL.PolicyEngine:
    cfg_dict = dict((head or {}).get("cfg") or {})
    cfg = CTL.ControlConfig(**cfg_dict) if cfg_dict else \
        CTL.ControlConfig.from_env()
    modes = (head or {}).get("modes") or []
    if args.modes is not None:
        modes = [m for m in args.modes.split(",") if m]
    initial = args.initial_mode or (head or {}).get("initial_mode")
    gamma = bool((head or {}).get("gamma")) or args.gamma
    return CTL.PolicyEngine(cfg, modes=modes, initial_mode=initial,
                            gamma=gamma,
                            cadence=(head or {}).get("cadence"))


def replay(prefix: str, *, head: Optional[dict] = None,
           engine: Optional[CTL.PolicyEngine] = None,
           every: Optional[int] = None,
           platform: Optional[str] = None,
           expected_ranks: Optional[int] = None,
           health_window: Optional[int] = None,
           mode: str = "shadow") -> List[CTL.Decision]:
    """Re-run the policy over a recorded run; returns the decision list.
    ``engine`` must be freshly constructed (the replay mutates it)."""
    if engine is None:
        raise ValueError("replay needs a PolicyEngine")
    head = head or {}
    every = every or head.get("every") or engine.cfg.every
    platform = platform or head.get("platform")
    if expected_ranks is None:
        expected_ranks = head.get("expected_ranks")
    # the live run's FULL health config rides the head record — a replay
    # must judge the telemetry with the thresholds the pod actually ran,
    # not the replaying machine's BLUEFOG_HEALTH_* environment
    if isinstance(head.get("health"), dict):
        hcfg = H.HealthConfig(**head["health"])
    else:
        hcfg = H.HealthConfig.from_env()
        if health_window or head.get("health_window"):
            hcfg.window = int(health_window or head.get("health_window"))
    # a controller fed by an edges ARTIFACT recorded the gated entries
    # in the head record (they never ride the telemetry JSONL)
    artifact_entries = head.get("artifact_entries")
    full = AG.load_fleet(prefix, expected_ranks=expected_ranks)
    steps = full.steps()
    if not steps:
        return []
    out: List[CTL.Decision] = []
    for t in range(steps[-1] + 1):
        if t % every != every - 1:
            continue
        view = _truncated_view(full, t - 1)
        report = H.evaluate(view, hcfg)
        edges = artifact_entries
        if edges is None:
            latest = view.latest_edges()
            if latest:
                rec_platform = latest.get("platform")
                # the same foreign-matrix guard the live controller
                # applies: entries probed on a different backend than
                # the run's are not a link model
                if not (rec_platform is not None and platform is not None
                        and rec_platform != platform):
                    edges = latest["entries"]
        for d in engine.evaluate(view, report, t, edges=edges):
            d.mode = mode
            out.append(d)
    return out


def _cmd_replay(args) -> int:
    expect_head, expect = (None, None)
    config_from = args.config_from
    if config_from is None:
        candidate = (args.expect if args.expect
                     else args.prefix + "decisions.jsonl")
        config_from = candidate
    head, recorded = CTL.read_decisions(config_from)
    if args.expect:
        expect_head, expect = CTL.read_decisions(args.expect)
        if head is None:
            head = expect_head
    engine = _engine_from(head, args)
    decisions = replay(
        args.prefix, head=head, engine=engine, every=args.every,
        platform=args.platform, expected_ranks=args.ranks,
        health_window=args.health_window, mode=args.mode)
    if args.out:
        desc = engine.describe()
        desc["every"] = args.every or (head or {}).get("every") \
            or engine.cfg.every
        desc["platform"] = args.platform or (head or {}).get("platform")
        CTL.write_config_record(args.out, desc, extra={"replayed": True})
        for d in decisions:
            CTL.write_decision(args.out, d)
    result = {
        "prefix": args.prefix,
        "n": len(decisions),
        "decisions": [d.asdict() for d in decisions],
    }
    rc = 0
    if args.expect is not None:
        want = [(r.get("step"), r.get("knob"), r.get("action"),
                 r.get("value"), r.get("rule")) for r in (expect or [])]
        got = [d.signature() for d in decisions]
        result["expect"] = args.expect
        result["match"] = (got == [tuple(w) for w in want])
        if not result["match"]:
            result["expected"] = want
            rc = 1
    print(json.dumps(result))
    return rc


def _show_schedule(args) -> int:
    """Render a synthesized schedule: rounds, offsets, and — when a
    cost matrix is at hand — the predicted per-round bottleneck costs.

    ``path`` is either a saved :class:`ScheduleIR` JSON file
    (``ScheduleIR.save``) or a decision trail whose latest ``kind:
    "schedule"`` record is rendered."""
    from ..control import synthesize as SYN
    from ..parallel.schedule_ir import ScheduleIR
    matrix = None
    if args.edges:
        from ..observability.commprof import EdgeCostMatrix
        matrix = EdgeCostMatrix.load(args.edges)
    ir = None
    with open(args.path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if (isinstance(doc, dict) and "rounds" in doc and "size" in doc
            and "kind" not in doc):   # a trail record is NOT a saved IR:
        ir = ScheduleIR.fromdict(doc)  # its rounds drop the self weights
    else:
        rec = None
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(r, dict) and r.get("kind") == "schedule":
                rec = r
        if rec is None:
            print(f"no schedule record in {args.path}")
            return 1
        print(f"schedule {rec.get('name', '?')!r} "
              f"source={rec.get('source')} period={rec.get('period')} "
              f"size={rec.get('size')} offsets={rec.get('offsets')}")
        print(f"fingerprint {rec.get('fingerprint')}")
        if rec.get("reason"):
            print(f"reason: {rec['reason']}")
        costs = rec.get("round_costs_us")
        for t, rnd in enumerate(rec.get("rounds", [])):
            edges = " ".join(f"{s}->{d}" for s, d, _ in rnd["edges"])
            tail = (f"  predicted {costs[t]:.1f} us"
                    if costs and t < len(costs) else "")
            print(f"round {t}: {edges or '(self only)'}{tail}")
        if rec.get("bottleneck_us") is not None:
            print(f"bottleneck: {rec['bottleneck_us']:.1f} us")
        return 0
    print(f"schedule {ir.name!r} period={ir.period} size={ir.size} "
          f"offsets={list(ir.offsets())} "
          f"permute_budget={ir.permute_budget(1)}")
    print(f"fingerprint {ir.fingerprint()}")
    costs = SYN.predicted_round_costs(ir, matrix) if matrix else None
    for t, rnd in enumerate(ir.rounds):
        edges = " ".join(f"{s}->{d}({w:.3g})" for s, d, w in rnd.edges)
        tail = f"  predicted {costs[t]:.1f} us" if costs else ""
        print(f"round {t}: {edges or '(self only)'}{tail}")
    if costs:
        print(f"bottleneck: {max(costs):.1f} us")
    return 0


def _cmd_show(args) -> int:
    if args.schedule:
        return _show_schedule(args)
    head, decisions = CTL.read_decisions(args.path)
    if head:
        print(f"config: modes={head.get('modes')} "
              f"initial={head.get('initial_mode')} "
              f"gamma={head.get('gamma')} every={head.get('every')} "
              f"platform={head.get('platform')}")
    if not decisions:
        print("(no decisions)")
        return 0
    for d in decisions:
        tag = "applied" if d.get("applied") else (
            "would" if d.get("mode") == "shadow" else "skipped")
        print(f"step {str(d.get('step', '-')):>6}  {d.get('knob')}:"
              f"{d.get('action')} {d.get('prev')} -> {d.get('value')}  "
              f"[{d.get('rule')}] ({tag})")
        if d.get("reason"):
            print(f"        {d['reason']}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bfctl",
        description="dry-run / replay the closed-loop controller over "
                    "recorded telemetry (docs/control.md)")
    sub = p.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser(
        "replay",
        help="re-run the policy over a recorded run's JSONL series")
    rp.add_argument("prefix",
                    help="metrics prefix: reads <prefix><rank>.jsonl")
    rp.add_argument("--expect", default=None, metavar="PATH",
                    help="live decision trail to reproduce: exit 1 on "
                         "any signature mismatch")
    rp.add_argument("--config-from", default=None, metavar="PATH",
                    help="decision trail whose control_config head "
                         "seeds the engine (default: --expect, else "
                         "<prefix>decisions.jsonl)")
    rp.add_argument("--out", default=None, metavar="PATH",
                    help="write the replayed trail to this JSONL")
    rp.add_argument("--every", type=int, default=None,
                    help="evaluation cadence override (steps)")
    rp.add_argument("--mode", choices=("shadow", "on"), default="shadow",
                    help="mode stamped on replayed decisions (replay "
                         "never actuates; default shadow)")
    rp.add_argument("--modes", default=None,
                    help="comma-separated schedule mode names override")
    rp.add_argument("--initial-mode", default=None)
    rp.add_argument("--gamma", action="store_true",
                    help="enable the gamma knob when no config record "
                         "says so")
    rp.add_argument("--platform", default=None,
                    help="platform the run's probes priced (guards "
                         "in-series edge records)")
    rp.add_argument("--ranks", type=int, default=None)
    rp.add_argument("--health-window", type=int, default=None)
    rp.set_defaults(fn=_cmd_replay)

    sh = sub.add_parser(
        "show",
        help="pretty-print a decision trail (or, with --schedule, a "
             "synthesized schedule)")
    sh.add_argument("path")
    sh.add_argument("--schedule", action="store_true",
                    help="render PATH as a schedule: a saved ScheduleIR "
                         "JSON file, or a trail whose latest "
                         "kind=schedule record is shown (rounds + "
                         "predicted bottleneck cost)")
    sh.add_argument("--edges", default=None, metavar="PATH",
                    help="edge-cost matrix JSON pricing the rounds "
                         "(with --schedule on a ScheduleIR file)")
    sh.set_defaults(fn=_cmd_show)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
