"""End-to-end SPMD train-step builder.

The reference overlaps communication with compute via torch forward/backward
hooks inside its optimizers (optimizers.py:354-414).  The TPU-native
equivalent is structural: build ONE jitted program containing forward,
backward, the decentralized exchange, and the optimizer update — XLA then
schedules the ppermute traffic concurrently with the update math, and every
step is a single dispatch.

Data layout: global view.  Parameters' leaves are [N, *S] (one replica per
rank, sharded over the mesh); batches are [N, B_local, ...].  BatchNorm
statistics stay rank-local like the reference's torch buffers (only
``broadcast_parameters`` ever syncs them).
"""

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import timeline as _tl
from .compress import compressors as _cp
from .compress import exchange as _cx
from .context import ctx
from .observability import export as _ex
from .observability import ingraph as IG
from .observability import phases as _phases
from .ops import api as _api
from .ops import fusion as _fusion
from .optim import strategies as S
from .optim._plumbing import mesh_plumbing
from .parallel.schedule import DynamicSchedule

__all__ = ["create_train_state", "make_train_step", "cross_entropy_loss",
           "replicate_to_ranks", "make_lm_train_step", "run_steps"]

# bflint knob-outside-cache-key: factory knobs that deliberately do NOT
# join _plumbing.step_cache_key.  make_train_step/create_train_state
# return a FRESH jitted callable / state layout per call — there is no
# shared step cache a stale program could be served from — so build-
# structural arguments (communication mode, loss, donation, vma check,
# local-step count, train flag, attention flavor) pin at construction;
# `sched` stays traced data (the step index selects the edge set inside
# one compiled program, docs/topology.md "Dynamic schedules").
_STEP_KEY_EXEMPT_KNOBS = frozenset({
    "loss_fn", "communication", "atc", "sched",
    "num_steps_per_communication", "donate", "check_vma", "train",
})


def cross_entropy_loss(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def replicate_to_ranks(tree, size: Optional[int] = None):
    """Tile a single-replica pytree to the global view [N, ...]."""
    n = size if size is not None else ctx().size
    return jax.tree.map(lambda a: jnp.broadcast_to(a[None], (n,) + a.shape),
                        tree)


def create_train_state(model, base_opt: optax.GradientTransformation,
                       rng, sample_input, train: bool = True,
                       communication: str = None,
                       overlap: Optional[bool] = None,
                       fuse: Optional[bool] = None,
                       fusion_bucket_bytes: Optional[int] = None,
                       compression=None):
    """Initialize (variables, opt_state) in global view.

    All ranks start from the same weights, matching the reference's
    ``bf.broadcast_parameters(model.state_dict(), root_rank=0)`` pattern.
    Pass the SAME ``communication`` you will give ``make_train_step`` when
    the strategy carries extra state (``exact_diffusion`` adds the
    psi_prev tree); for every other mode the argument is ignored.

    ``overlap`` (default ``BLUEFOG_COMM_OVERLAP``, off): the overlapped
    stepper carries its in-flight exchange buffers in the opt state —
    pass the same ``overlap``/``fuse``/``fusion_bucket_bytes`` you will
    give ``make_train_step`` so the carried-buffer layout matches the
    step that donates it.

    ``compression`` (default ``BLUEFOG_COMM_COMPRESS``, off): stateful
    configs (lossy / choco) carry residual/estimate buffers in the opt
    state — pass the same ``compression`` (and fusion knobs) you will
    give ``make_train_step``, for the same layout reason as ``overlap``.
    """
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    gparams = replicate_to_ranks(params)
    gextra = replicate_to_ranks(extra)
    cfg = _cp.resolve_compression(compression)
    if S.overlap_enabled(overlap):
        # the ONE definition of the pipeline state layout (warmup in-flight
        # buffers + optional psi_prev + compression residuals) lives in
        # strategies.delayed_init
        opt_state = jax.vmap(lambda p: S.delayed_init(
            base_opt, p, fuse=fuse,
            fusion_bucket_bytes=fusion_bucket_bytes,
            exact_diffusion=communication == "exact_diffusion",
            compression=cfg))(gparams)
    elif communication == "exact_diffusion":
        # the ONE definition of the ED state layout lives in strategies.py
        # (psi_prev copied there: params+opt_state donation stays legal)
        opt_state = jax.vmap(
            lambda p: S.exact_diffusion_init(
                base_opt, p, compression=cfg, fuse=fuse,
                fusion_bucket_bytes=fusion_bucket_bytes))(gparams)
    elif _cx.stateful(cfg):
        # every make_train_step strategy that carries compression state
        # wraps it as {"base", "compress"} (grad-AR accumulation is the
        # wrapper-optimizer path, rejected by make_train_step)
        opt_state = jax.vmap(lambda p: S.compress_wrap_init(
            base_opt, p, cfg, fuse=fuse,
            fusion_bucket_bytes=fusion_bucket_bytes))(gparams)
    else:
        opt_state = jax.vmap(base_opt.init)(gparams)
    return {"params": gparams, **gextra}, opt_state


def make_train_step(model,
                    base_opt: optax.GradientTransformation,
                    loss_fn: Callable = cross_entropy_loss,
                    communication: str = "neighbor_allreduce",
                    atc: bool = False,
                    sched: Optional[DynamicSchedule] = None,
                    num_steps_per_communication: int = 1,
                    donate: bool = True,
                    check_vma: Optional[bool] = None,
                    fuse: Optional[bool] = None,
                    fusion_bucket_bytes: Optional[int] = None,
                    overlap: Optional[bool] = None,
                    telemetry: Optional[bool] = None,
                    compression=None,
                    gossip_kernel=None):
    """Build the jitted global train step.

    ``communication``: one of ``neighbor_allreduce`` (default, decentralized
    CTA), ``allreduce`` (CTA on weights), ``gradient_allreduce`` (Horovod
    style), ``hierarchical_neighbor_allreduce``, ``exact_diffusion``
    (bias-corrected ATC, static topology only — create the opt_state with
    ``create_train_state(..., communication="exact_diffusion")``),
    ``empty`` (local only).

    ``fuse`` (default: ``BLUEFOG_COMM_FUSION``, on): run the exchange over
    dtype-bucketed flat buffers (``ops/fusion.py``) — collective count per
    step drops from ``leaves x offsets`` to ``buckets x offsets`` with
    bit-exact results; ``fusion_bucket_bytes`` tunes the bucket cap
    (``docs/performance.md``).  Both snapshot at build time, like the
    exchange backend.

    ``overlap`` (default ``BLUEFOG_COMM_OVERLAP``, off): staleness-1
    delayed-mix pipeline — the step folds the PREVIOUS step's exchange
    result (carried in the donated opt state as fused flat buffers) and
    launches this step's exchange off the critical path, so XLA schedules
    the ppermute traffic concurrently with forward/backward
    (docs/performance.md "Overlap").  Supported for ``neighbor_allreduce``
    / ``allreduce`` / ``exact_diffusion`` with
    ``num_steps_per_communication=1``; create the opt state with
    ``create_train_state(..., overlap=True)``.  Step 0 is a documented
    warmup (local-only) step.

    ``compression`` (default ``BLUEFOG_COMM_COMPRESS``, off): compress
    the exchange wire over the fused buckets — ``"int8"``/``"fp8"``
    quantization, ``"topk:0.01"``/``"randomk:0.05"`` sparsification, or
    ``"choco:<spec>[:gamma=G]"`` difference gossip (``docs/
    compression.md``).  Lossy configs carry error-feedback residuals in
    the donated opt state: create it with ``create_train_state(...,
    compression=...)``.  ``None``/off lowers to byte-identical StableHLO
    versus the pre-compression step (asserted by
    ``tests/test_compress.py``).

    ``gossip_kernel`` (default ``BLUEFOG_GOSSIP_KERNEL``, off): run the
    compressed neighbor exchange as ONE fused Pallas kernel per fusion
    bucket — quantize-on-store, concurrent wire RDMAs to all neighbors,
    decode-on-load, in-register mix + EF residual (``docs/performance.md``
    "Single-kernel gossip").  Needs a dense-quantizer ``compression``
    (``int8``/``fp8``) and fused buckets; modes ``"pallas"`` (TPU),
    ``"interpret"`` (CPU test mesh, jaxlib >= 0.5), ``"emulate"``
    (ppermute transport, any backend).  Bit-exact vs the chain; off
    lowers byte-identical StableHLO.

    ``telemetry`` (default ``BLUEFOG_TELEMETRY``, off): compute traced
    training-health aggregates INSIDE the step — consensus distance
    ``||x_i - x_bar||^2`` (one pmean per fusion bucket), mixing-matrix
    column/row mass, param/grad/update norms, overlap staleness/warmup
    flags — returned as a 4th output, a per-rank
    ``observability.ingraph.TelemetrySnapshot`` with ``[N]`` fields
    (docs/observability.md).  Off lowers to bit-identical StableHLO
    (asserted by ``tests/test_observability.py``).

    Returns ``train_step(variables, opt_state, batch, step) ->
    (variables, opt_state, loss)`` — plus the telemetry snapshot when
    ``telemetry`` resolves on — where ``batch = (x, y)`` with leading
    [N, B_local] dims and ``loss`` is the cross-rank mean.
    """
    cx = ctx()
    hierarchical = communication == "hierarchical_neighbor_allreduce"
    grad_ar = communication == "gradient_allreduce"
    exact_diffusion = communication == "exact_diffusion"
    comm_type = {
        "neighbor_allreduce": S.CommunicationType.neighbor_allreduce,
        "allreduce": S.CommunicationType.allreduce,
        "hierarchical_neighbor_allreduce":
            S.CommunicationType.hierarchical_neighbor_allreduce,
        "gradient_allreduce": S.CommunicationType.empty,
        "exact_diffusion": S.CommunicationType.neighbor_allreduce,
        "empty": S.CommunicationType.empty,
    }[communication]

    if exact_diffusion and sched is not None:
        raise ValueError(
            "exact_diffusion requires a static topology: the correction "
            "diverges under dynamic schedules (see "
            "DistributedExactDiffusionOptimizer)")
    topo = cx.compiled_topology if (
        comm_type == S.CommunicationType.neighbor_allreduce and sched is None
    ) else None
    machine_topo = cx.compiled_machine_topology if hierarchical else None

    # the exchange backend and fusion knobs bind when the step is BUILT
    # (jit traces once; reading the env at trace time would freeze whatever
    # the first call saw and silently ignore later env changes)
    nar_backend = _api._nar_backend()
    fuse = _fusion.fusion_enabled(fuse)
    fusion_bucket_bytes = _fusion.resolve_max_bucket_bytes(
        fusion_bucket_bytes)
    overlap = S.overlap_enabled(overlap)
    telemetry = IG.telemetry_enabled(telemetry)
    compression = _cp.resolve_compression(compression)
    _cx.check_supported(
        compression,
        comm_value="allreduce" if grad_ar else comm_type.value,
        sched=sched, overlap=overlap)
    # validated here for fail-fast + the check_vma decision below; the
    # strategy builders re-derive the same (mode, interleave) pair from
    # the raw knob
    gk_mode, _ = _cx.effective_gossip_kernel(
        gossip_kernel, compression,
        comm_value="allreduce" if grad_ar else comm_type.value, fuse=fuse)
    if overlap:
        if communication not in ("neighbor_allreduce", "allreduce",
                                 "exact_diffusion"):
            raise ValueError(
                f"overlap=True supports neighbor_allreduce / allreduce / "
                f"exact_diffusion, got {communication!r} (gradient "
                f"averaging has no weight exchange to pipeline; "
                f"hierarchical's two-level mix has no single in-flight "
                f"self weight)")
        if num_steps_per_communication > 1:
            raise ValueError(
                "overlap=True assumes one exchange per step "
                "(num_steps_per_communication=1)")
    if check_vma is None:
        # any pallas kernel inside the shard_map needs vma checking off
        # (kernel-internal scratch carries no varying-axes tags): the
        # fused exchange backend, or a model carrying pallas kernels —
        # detected by the `contains_pallas` marker on the model or its
        # block class (e.g. FusedBottleneckBlock).  Custom pallas-bearing
        # models without the marker pass check_vma=False explicitly.
        model_pallas = bool(
            getattr(model, "contains_pallas", False)
            or getattr(getattr(model, "block_cls", None),
                       "contains_pallas", False))
        check_vma = not (nar_backend.startswith("pallas") or model_pallas
                         or gk_mode in ("pallas", "interpret"))
    if overlap:
        if exact_diffusion:
            core = S.delayed_exact_diffusion_step(
                base_opt, comm_type, cx.rank_axis,
                topo=S.exact_diffusion_topology(cx.compiled_topology),
                machine_axes=(cx.machine_axis, cx.local_axis),
                machine_topo=machine_topo, nar_backend=nar_backend,
                fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes,
                telemetry=telemetry, compression=compression,
                gossip_kernel=gossip_kernel)
        else:
            builder = S.delayed_atc_step if atc else S.delayed_consensus_step
            core = builder(base_opt, comm_type, cx.rank_axis, topo=topo,
                           sched=sched,
                           machine_axes=(cx.machine_axis, cx.local_axis),
                           machine_topo=machine_topo,
                           nar_backend=nar_backend, fuse=fuse,
                           fusion_bucket_bytes=fusion_bucket_bytes,
                           telemetry=telemetry, compression=compression,
                           gossip_kernel=gossip_kernel)
    elif grad_ar:
        if num_steps_per_communication > 1:
            raise ValueError(
                "gradient accumulation (num_steps_per_communication > 1 with "
                "gradient_allreduce) needs the accumulator state — use "
                "bf.DistributedGradientAllreduceOptimizer instead")
        core = S.gradient_allreduce_step(
            base_opt, cx.rank_axis, fuse=fuse,
            fusion_bucket_bytes=fusion_bucket_bytes, telemetry=telemetry,
            compression=compression)
    elif exact_diffusion:
        if num_steps_per_communication > 1:
            raise ValueError("exact_diffusion assumes one exchange per "
                             "adapt step (num_steps_per_communication=1)")
        # symmetric-topology validation + (I+W)/2 damping (see
        # S.exact_diffusion_topology: the undamped directed recursion
        # measurably diverges)
        core = S.exact_diffusion_step(
            base_opt, comm_type, cx.rank_axis,
            topo=S.exact_diffusion_topology(cx.compiled_topology),
            machine_axes=(cx.machine_axis, cx.local_axis),
            machine_topo=machine_topo, nar_backend=nar_backend,
            fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes,
            telemetry=telemetry, compression=compression,
            gossip_kernel=gossip_kernel)
    else:
        builder = S.atc_step if atc else S.consensus_step
        core = builder(base_opt, comm_type, cx.rank_axis, topo=topo,
                       sched=sched,
                       machine_axes=(cx.machine_axis, cx.local_axis),
                       machine_topo=machine_topo, nar_backend=nar_backend,
                       fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes,
                       telemetry=telemetry, compression=compression,
                       gossip_kernel=gossip_kernel)
    if not (exact_diffusion or overlap):
        tel_axis = S._telemetry_axis(
            comm_type, cx.rank_axis, (cx.machine_axis, cx.local_axis))
        core = S.with_local_steps(
            core,
            S.local_sgd_like_step(base_opt, telemetry=telemetry,
                                  axis_name=tel_axis, fuse=fuse,
                                  fusion_bucket_bytes=fusion_bucket_bytes,
                                  compression=compression),
            num_steps_per_communication)

    pl = mesh_plumbing(cx, hierarchical)

    def stepper(variables, opt_state, batch, step_idx):
        def shard_fn(vars_s, opt_s, batch_s, si):
            v = pl.unwrap(vars_s)
            st = pl.unwrap(opt_s)
            x, y = pl.unwrap(batch_s)
            params = v["params"]
            extra = {k: s for k, s in v.items() if k != "params"}

            def local_loss(p):
                out = model.apply({"params": p, **extra}, x, train=True,
                                  mutable=list(extra.keys()) or False)
                if extra:
                    logits, new_extra = out
                else:
                    logits, new_extra = out, {}
                return loss_fn(logits, y), new_extra

            (loss, new_extra), grads = jax.value_and_grad(
                local_loss, has_aux=True)(params)
            if telemetry:
                params_new, st_new, snap = core(params, grads, st, si)
            else:
                params_new, st_new = core(params, grads, st, si)
            mean_loss = jax.lax.pmean(
                loss, cx.rank_axis if not hierarchical
                else (cx.machine_axis, cx.local_axis))
            v_new = {"params": params_new, **new_extra}
            if telemetry:
                return (pl.rewrap(v_new), pl.rewrap(st_new), mean_loss,
                        pl.rewrap(snap))
            return pl.rewrap(v_new), pl.rewrap(st_new), mean_loss

        v2, o2 = pl.reshape_in(variables), pl.reshape_in(opt_state)
        b2 = pl.reshape_in(batch)
        # telemetry adds one sharded output (the snapshot) after the loss
        out_specs = ((pl.spec, pl.spec, P(), pl.spec) if telemetry
                     else (pl.spec, pl.spec, P()))
        # check_vma off under the pallas backend: the fused-exchange
        # kernel's outputs carry no varying-manual-axes tags (same
        # exemption as ops/api.py's _shardmapped pallas path)
        out = jax.shard_map(
            shard_fn, mesh=pl.mesh,
            in_specs=(pl.spec, pl.spec, pl.spec, P()),
            out_specs=out_specs,
            check_vma=check_vma,
        )(v2, o2, b2, step_idx)
        return tuple(o if i == 2 else pl.reshape_out(o)
                     for i, o in enumerate(out))

    return jax.jit(stepper, donate_argnums=(0, 1) if donate else ())


def run_steps(step_fn, variables, opt_state, batches, num_steps: int, *,
              start_step: int = 0, log: bool = True):
    """Drive a :func:`make_train_step` function as an instrumented
    host-side step loop.

    Each iteration runs the jitted dispatch under the ``compute``
    step-phase timer (``observability/phases.py``) and — when a JSONL
    sink or timeline is open — exports the step's telemetry, loss, step
    wall time, and phase timings via ``export.log_step``, which is all
    ``bfmonitor`` / the fleet health engine need to watch the run live
    (docs/observability.md "Fleet health & bfmonitor").  With
    observability off this is a plain loop: the phase timer is one bool
    check and ``log_step`` returns immediately.

    ``batches``: a fixed global batch or a callable ``step -> batch``.
    Returns ``(variables, opt_state, losses)``.
    """
    batch_of = batches if callable(batches) else (lambda _t: batches)
    losses = []
    for t in range(start_step, start_step + num_steps):
        # the gossip-round span (sync'd by the loss fetch below) is the
        # per-round anchor bftrace matches across ranks to align clocks
        tok = _tl.op_start_us()
        with _phases.step_phase("compute"):
            out = step_fn(variables, opt_state, batch_of(t),
                          jnp.asarray(t, jnp.int32))
            variables, opt_state, loss = out[0], out[1], out[2]
            snap = out[3] if len(out) > 3 else None
            # the scalar fetch is the device sync: jit dispatch returns
            # immediately, so timing it alone would attribute the whole
            # device execution to no phase
            loss = float(loss)
        _tl.record_gossip_round(t, tok)
        losses.append(loss)
        if log:
            _ex.log_step(t, snap, extra={"loss": loss})
    return variables, opt_state, losses


def make_lm_train_step(model, base_opt: optax.GradientTransformation,
                       attn: str = "ring", donate: bool = True):
    """Sequence-parallel language-model train step (long-context path).

    Tokens/targets [B, T] are sharded along the sequence over the rank mesh
    axis; parameters are replicated.  Each rank runs the Transformer on its
    sequence shard with ``attn`` in {"ring", "ulysses"} providing exact
    global attention (``ops/ring_attention.py``), gradients are psum'd over
    the axis, and one optimizer step updates the replicated parameters.
    Context length therefore scales linearly with the mesh while per-chip
    activation memory stays constant.

    Returns ``step(params, opt_state, tokens, targets) ->
    (params, opt_state, loss)``; requires ``T %% size == 0``.
    """
    from .ops.ring_attention import ring_attention, ulysses_attention
    from .ops.moe import expert_parallel_ffn

    cx = ctx()
    axis = cx.rank_axis
    if attn not in ("ring", "ulysses"):
        raise ValueError(f"attn must be 'ring' or 'ulysses', got {attn!r}")
    attn_impl = ring_attention if attn == "ring" else ulysses_attention
    cfg = getattr(model, "config", None)
    num_experts = getattr(cfg, "num_experts", 0)
    if num_experts and num_experts % cx.size:
        raise ValueError(
            f"num_experts {num_experts} must be divisible by the mesh "
            f"size {cx.size} for expert parallelism")

    # The global loss is a shard_map whose output is the cross-rank pmean;
    # differentiating THROUGH it (grad outside, forward inside) lets the
    # shard_map transpose route KV-hop cotangents between ranks and psum the
    # replicated-parameter cotangent exactly once.  (Taking jax.grad *inside*
    # the body instead silently double-counts: grad w.r.t. an unvarying
    # input is auto-psummed across ranks by the pcast transpose.)
    _EXPERT_KEYS = ("w_up", "b_up", "w_down", "b_down")

    def _split_experts(p):
        """(expert tables, rest-of-params): the tables leave the flax tree
        so they can enter the shard_map SHARDED over the rank axis — flax's
        apply-time shape check would reject an E/n-shaped leaf inside the
        params tree, so they ride the ``expert_params`` argument instead
        (models/transformer.py)."""
        experts, rest = {}, {}
        for k, v in p.items():
            if k.startswith("block_") and isinstance(v, dict) and "moe" in v:
                moe = v["moe"]
                experts[k] = {n: moe[n] for n in _EXPERT_KEYS if n in moe}
                rest[k] = {**{kk: vv for kk, vv in v.items() if kk != "moe"},
                           "moe": {n: w for n, w in moe.items()
                                   if n not in _EXPERT_KEYS}}
            else:
                rest[k] = v
        return experts, rest

    def global_loss(p, tokens, targets):
        if tokens.shape[1] % cx.size:
            raise ValueError(
                f"sequence length {tokens.shape[1]} must be divisible by "
                f"the mesh size {cx.size} for sequence parallelism")

        def shard_fn(p_, experts_, tok, tgt):
            shard_len = tok.shape[1]
            offset = jax.lax.axis_index(axis) * shard_len
            attn_fn = lambda q, k, v: attn_impl(q, k, v, axis, causal=True)

            # expert parallelism: each rank computes only its E/n experts;
            # two all-to-alls move the routed token slots (ops/moe.py).
            # Expert parameter leaves enter this shard_map SHARDED over the
            # rank axis (in_specs below), so each rank's tree already holds
            # only its E/n experts — EP saves expert memory, not just
            # compute; the shard_map transpose delivers each expert's grads
            # to exactly its owning rank.
            def moe_fn(x2, logits2, expert_fn, eparams):
                return expert_parallel_ffn(
                    x2, logits2, expert_fn, eparams, axis,
                    capacity_factor=getattr(cfg, "capacity_factor", 1.25))

            kwargs = dict(attn_fn=attn_fn, position_offset=offset)
            if num_experts:
                out, inter = model.apply(
                    {"params": p_}, tok, moe_fn=moe_fn,
                    expert_params=experts_,
                    mutable=["intermediates"], **kwargs)
                # only the router's sown aux losses — a future sow of any
                # other diagnostic must not leak into the training loss
                aux = sum(
                    leaf for path, leaf in
                    jax.tree_util.tree_flatten_with_path(inter)[0]
                    if "moe_aux_loss" in jax.tree_util.keystr(path))
            else:
                out = model.apply({"params": p_}, tok, **kwargs)
                aux = 0.0
            loss = optax.softmax_cross_entropy_with_integer_labels(
                out, tgt).mean() + 0.01 * aux
            return jax.lax.pmean(loss, axis)

        experts, rest = _split_experts(p) if num_experts else ({}, p)
        # expert tables shard over the rank axis (dim 0 = experts): each
        # rank's shard_map body receives only its E/n experts — EP scales
        # expert MEMORY with the mesh, not just compute (VERDICT r1 weak 7)
        expert_specs = jax.tree.map(lambda _: P(cx.rank_axis), experts)
        return jax.shard_map(
            shard_fn, mesh=cx.mesh,
            in_specs=(P(), expert_specs, P(None, cx.rank_axis),
                      P(None, cx.rank_axis)),
            out_specs=P())(rest, experts, tokens, targets)

    def stepper(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(global_loss)(params, tokens, targets)
        updates, opt_new = base_opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_new, loss

    return jax.jit(stepper, donate_argnums=(0, 1) if donate else ())
