"""Decoder-only Transformer (long-context / sequence-parallel model family).

The reference has no attention model (SURVEY.md §5.7); this family exists to
exercise the framework's first-class sequence parallelism: the attention
layer is pluggable, so the same module runs single-device (full attention)
or inside ``shard_map`` with ``ops.ring_attention`` / ``ops.ulysses_attention``
over a sequence mesh axis.  TPU-first choices: bfloat16 compute with float32
params, GELU MLP with 4x expansion (MXU-friendly matmul shapes), rotary
position embeddings (work on per-shard blocks via a position offset — no
learned position table to shard).
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..ops.ring_attention import attention as _full_attention

__all__ = ["Transformer", "TransformerConfig", "TransformerLM"]

Dtype = Any


def _rope(x, positions, *, base: float = 10000.0):
    """Rotary position embedding on [B, T, H, D] with int positions [T]."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-np.arange(0, half, dtype=np.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class TransformerConfig:
    """Static hyperparameters (kept out of the Module so jit sees one leaf)."""

    def __init__(self, vocab_size=32000, num_layers=4, num_heads=8,
                 embed_dim=512, mlp_ratio=4, max_len=8192,
                 dtype=jnp.bfloat16, num_experts=0, capacity_factor=1.25,
                 attn_impl="auto", remat=False, num_kv_heads=None):
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads        # None = MHA; < num_heads = GQA
        self.embed_dim = embed_dim
        self.mlp_ratio = mlp_ratio
        self.max_len = max_len
        self.dtype = dtype
        self.num_experts = num_experts          # 0 = dense MLP
        self.capacity_factor = capacity_factor
        # default attention when no attn_fn is injected: "auto" picks the
        # Pallas flash kernel on TPU (ops/flash_attention.py), the XLA
        # reference path elsewhere; "flash"/"reference" force a choice
        if attn_impl not in ("auto", "flash", "reference"):
            raise ValueError(
                f"attn_impl must be 'auto', 'flash' or 'reference', "
                f"got {attn_impl!r}")
        self.attn_impl = attn_impl
        # rematerialize each block in the backward pass: activation memory
        # drops from O(layers) to O(1) blocks at ~1/3 extra FLOPs — the
        # standard lever for long-context/batch scaling on fixed HBM
        self.remat = remat


class MoEMLP(nn.Module):
    """Switch-style mixture-of-experts MLP (ops/moe.py).

    ``moe_fn(x2d, logits, expert_fn, params) -> (out2d, aux)`` selects the
    execution strategy: ``None`` runs every expert locally
    (``local_moe_ffn``); the expert-parallel train step passes a closure
    over ``expert_parallel_ffn`` that slices this rank's experts and
    all-to-alls the token slots.
    """
    num_experts: int
    dtype: Dtype
    mlp_ratio: int = 4
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x, moe_fn: Optional[Callable] = None,
                 expert_params=None):
        from ..ops.moe import local_moe_ffn
        B, T, D = x.shape
        H, E = D * self.mlp_ratio, self.num_experts
        logits = nn.Dense(E, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32)).reshape(B * T, E)
        if expert_params is not None:
            # expert tables injected from outside flax (the SP+EP train
            # step shards them over the mesh — each rank passes only its
            # E/n experts, which flax's apply-time shape check would
            # otherwise reject; training.py:make_lm_train_step)
            w_up, b_up = expert_params["w_up"], expert_params["b_up"]
            w_down, b_down = expert_params["w_down"], expert_params["b_down"]
        else:
            w_up = self.param("w_up", nn.initializers.lecun_normal(),
                              (E, D, H))
            b_up = self.param("b_up", nn.initializers.zeros_init(), (E, H))
            w_down = self.param("w_down", nn.initializers.lecun_normal(),
                                (E, H, D))
            b_down = self.param("b_down", nn.initializers.zeros_init(),
                                (E, D))
        dt = self.dtype

        def expert_fn(params, h):
            wu, bu, wd, bd = params
            h = jnp.einsum("sd,dh->sh", h, wu.astype(dt)) + bu.astype(dt)
            h = nn.gelu(h)
            return jnp.einsum("sh,hd->sd", h, wd.astype(dt)) + bd.astype(dt)

        params = (w_up, b_up, w_down, b_down)
        x2 = x.reshape(B * T, D).astype(dt)
        if moe_fn is None:
            out, aux = local_moe_ffn(x2, logits, expert_fn, params,
                                     self.capacity_factor)
        else:
            out, aux = moe_fn(x2, logits, expert_fn, params)
        self.sow("intermediates", "moe_aux_loss", aux)
        return out.reshape(B, T, D)


class Block(nn.Module):
    """Pre-LN decoder block with a pluggable attention function.

    ``num_kv_heads`` < ``num_heads`` gives grouped-query attention (the
    modern KV-cache-lean layout; 1 = multi-query): q keeps every head,
    k/v project to the smaller count and the attention fn broadcasts
    (ops/flash_attention.py::_expand_kv_groups)."""
    num_heads: int
    dtype: Dtype
    mlp_ratio: int = 4
    num_experts: int = 0
    capacity_factor: float = 1.25
    num_kv_heads: Optional[int] = None

    @nn.compact
    def __call__(self, x, attn_fn: Callable, positions,
                 moe_fn: Optional[Callable] = None, expert_params=None):
        D = x.shape[-1]
        head_dim = D // self.num_heads
        kv_heads = (self.num_kv_heads if self.num_kv_heads is not None
                    else self.num_heads)
        if kv_heads < 1 or self.num_heads % kv_heads:
            raise ValueError(f"num_kv_heads ({kv_heads}) must be a "
                             f"positive divisor of num_heads "
                             f"({self.num_heads})")
        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        if kv_heads == self.num_heads:
            qkv = nn.DenseGeneral((3, self.num_heads, head_dim), axis=-1,
                                  dtype=self.dtype, name="qkv")(h)
            q, k, v = (qkv[..., i, :, :] for i in range(3))
        else:
            q = nn.DenseGeneral((self.num_heads, head_dim), axis=-1,
                                dtype=self.dtype, name="q")(h)
            kv = nn.DenseGeneral((2, kv_heads, head_dim), axis=-1,
                                 dtype=self.dtype, name="kv")(h)
            k, v = kv[..., 0, :, :], kv[..., 1, :, :]
        q = _rope(q, positions)
        k = _rope(k, positions)
        if kv_heads != self.num_heads:
            # expand here so every pluggable attn_fn (flash, ring,
            # ulysses, custom) keeps its equal-heads contract; the
            # repeated views are consumed immediately
            from ..ops.flash_attention import _expand_kv_groups
            k, v = _expand_kv_groups(q, k, v)
        a = attn_fn(q, k, v)
        a = nn.DenseGeneral(D, axis=(-2, -1), dtype=self.dtype,
                            name="proj")(a)
        x = x + a
        h = nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x)
        if self.num_experts:
            h = MoEMLP(self.num_experts, self.dtype, self.mlp_ratio,
                       self.capacity_factor, name="moe")(h, moe_fn,
                                                         expert_params)
        else:
            h = nn.Dense(D * self.mlp_ratio, dtype=self.dtype,
                         name="mlp_up")(h)
            h = nn.gelu(h)
            h = nn.Dense(D, dtype=self.dtype, name="mlp_down")(h)
        return x + h


class Transformer(nn.Module):
    """Decoder-only LM backbone returning logits.

    ``attn_fn(q, k, v)`` defaults to causal full attention.  For sequence
    parallelism, call inside ``shard_map`` with
    ``attn_fn=lambda q,k,v: ring_attention(q,k,v,"sp",causal=True)`` and pass
    ``position_offset = axis_index("sp") * shard_len`` so RoPE sees global
    positions.
    """
    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens, *, attn_fn: Optional[Callable] = None,
                 position_offset=0, moe_fn: Optional[Callable] = None,
                 expert_params=None):
        """``expert_params``: optional ``{"block_i": {w_up, b_up, w_down,
        b_down}}`` expert tables injected around flax (possibly sharded to
        this rank's experts); absent entries fall back to the params tree."""
        cfg = self.config
        if tokens.shape[1] > cfg.max_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds max_len "
                f"{cfg.max_len} (under sequence parallelism the per-shard "
                f"length is checked; size the config for the global context)")
        if attn_fn is None:
            from ..ops.flash_attention import best_attention
            if cfg.attn_impl == "reference":
                attn_fn = lambda q, k, v: _full_attention(q, k, v, causal=True)
            else:
                attn_fn = lambda q, k, v: best_attention(
                    q, k, v, causal=True,
                    force_flash=cfg.attn_impl == "flash")
        positions = position_offset + jnp.arange(tokens.shape[1])
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype,
                     name="embed")(tokens)
        # static_argnums: attn_fn/moe_fn are Python callables (arg 0 is
        # self); x/positions/expert_params are traced
        block_cls = (nn.remat(Block, static_argnums=(2, 4))
                     if cfg.remat else Block)
        for i in range(cfg.num_layers):
            ep = (expert_params or {}).get(f"block_{i}")
            x = block_cls(cfg.num_heads, cfg.dtype, cfg.mlp_ratio,
                          cfg.num_experts, cfg.capacity_factor,
                          num_kv_heads=getattr(cfg, "num_kv_heads", None),
                          name=f"block_{i}")(x, attn_fn, positions, moe_fn,
                                             ep)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                          name="lm_head")(x)
        return logits


def TransformerLM(**kwargs) -> Transformer:
    """Convenience constructor: ``TransformerLM(num_layers=4, ...)``."""
    return Transformer(TransformerConfig(**kwargs))
