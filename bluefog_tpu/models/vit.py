"""Vision Transformer (image model family #2, attention-based).

The reference's model zoo is torchvision's (examples/pytorch_resnet.py uses
``getattr(models, args.model)`` — ResNet and friends); this adds the
attention-family image model the TPU build favors: patchify with a single
strided conv (one big MXU matmul), then the same pre-LN decoder blocks as
the LM family (models/transformer.py) running bidirectionally, mean-pool
head.  Flash attention dispatches automatically on TPU via
``ops.flash_attention.best_attention`` (non-causal).

TPU-first choices: NHWC input, bfloat16 compute / float32 params, patch
and embed sizes that tile onto the 128-lane MXU.
"""

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .transformer import Block

__all__ = ["ViT", "ViT_S16", "ViT_B16"]


class ViT(nn.Module):
    """Patchified Transformer classifier.

    ``x``: [B, H, W, 3] with H, W divisible by ``patch``.
    """
    num_classes: int = 1000
    patch: int = 16
    num_layers: int = 12
    num_heads: int = 6
    embed_dim: int = 384
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        B, H, W, _ = x.shape
        if H % self.patch or W % self.patch:
            raise ValueError(
                f"image size {(H, W)} must be divisible by patch "
                f"{self.patch}")
        x = x.astype(self.dtype)
        # patchify: one strided conv == the unfold+project matmul
        x = nn.Conv(self.embed_dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, param_dtype=jnp.float32,
                    name="patch_embed")(x)
        T = (H // self.patch) * (W // self.patch)
        x = x.reshape(B, T, self.embed_dim)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, T, self.embed_dim), jnp.float32)
        x = x + pos.astype(self.dtype)

        from ..ops.flash_attention import best_attention
        attn_fn = lambda q, k, v: best_attention(q, k, v, causal=False)
        positions = jnp.zeros((T,), jnp.int32)  # RoPE off: learned pos above

        for i in range(self.num_layers):
            x = Block(self.num_heads, self.dtype, self.mlp_ratio,
                      name=f"block_{i}")(x, attn_fn, positions)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        x = x.mean(axis=1)
        # float32 head like the LM family: bf16 logits would quantize the
        # loss before the cast could help
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


ViT_S16 = partial(ViT, patch=16, num_layers=12, num_heads=6, embed_dim=384)
ViT_B16 = partial(ViT, patch=16, num_layers=12, num_heads=12, embed_dim=768)
