"""Small MLP / logistic-regression models used by tests and the
optimization example (reference parity: examples/pytorch_optimization.py)."""

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["MLP", "LogisticRegression"]


class MLP(nn.Module):
    features: Sequence[int] = (64, 64)
    num_outputs: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for f in self.features:
            x = nn.relu(nn.Dense(f, dtype=self.dtype)(x))
        return nn.Dense(self.num_outputs, dtype=self.dtype)(x).astype(jnp.float32)


class LogisticRegression(nn.Module):
    num_outputs: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        return nn.Dense(self.num_outputs)(x.reshape((x.shape[0], -1)))
