"""ResNet family (flagship benchmark model).

The reference benchmarks torchvision's ResNet-50 on synthetic ImageNet
(``examples/pytorch_benchmark.py``, ``examples/pytorch_resnet.py``); this is
a TPU-first Flax implementation: NHWC layout (TPU-native), optional bfloat16
compute with float32 parameters/statistics, and 3x3/1x1 convs sized to tile
onto the MXU.
"""

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ResNet152"]

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152)."""
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 block (ResNet-18/34)."""
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """NHWC ResNet with configurable stage sizes.

    ``dtype`` controls activation/compute precision (bfloat16 recommended on
    TPU); parameters and batch statistics stay float32.
    """
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32, axis_name=None)

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="norm_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=self.act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
