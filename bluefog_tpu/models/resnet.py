"""ResNet family (flagship benchmark model).

The reference benchmarks torchvision's ResNet-50 on synthetic ImageNet
(``examples/pytorch_benchmark.py``, ``examples/pytorch_resnet.py``); this is
a TPU-first Flax implementation: NHWC layout (TPU-native), optional bfloat16
compute with float32 parameters/statistics, and 3x3/1x1 convs sized to tile
onto the MXU.
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ResNet152", "ResNet50Fused", "FusedBottleneckBlock"]

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152)."""
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class FusedBottleneckBlock(nn.Module):
    """Bottleneck with the 1x1-conv BN passes fused (ops/conv_bn.py — the
    HBM-roofline attack, docs/performance.md):

    * conv1 (1x1) runs as ``matmul_bn_stats`` — BN1's reduce rides the
      conv's output write instead of re-reading HBM;
    * BN2 -> ReLU -> conv3 (1x1) -> BN3-stats runs as
      ``bn_relu_matmul_stats`` — the standalone normalize pass and BN3's
      reduce both disappear;
    * the 3x3 conv, projection shortcut, and elementwise glue stay XLA.

    Per block that removes three full activation passes of the four BN
    adds.  Gradients are exact (hand-written per-kernel VJPs); running
    statistics update exactly like ``nn.BatchNorm`` (the norm partial's
    momentum/epsilon, falling back to nn.BatchNorm's own defaults;
    biased batch variance).  Eval mode (``use_running_average``) takes
    the plain XLA composition with the same parameters.
    """
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    force_xla: bool = False   # exact XLA twin of the train path (ablation)

    # marker consumed by make_train_step: pallas kernels inside the
    # shard_map need check_vma off
    contains_pallas = True

    def _norm_config(self):
        """use_running_average / momentum / epsilon from the ``norm``
        ModuleDef.  The fused path re-implements BN around the kernels,
        so it must SEE the configuration — which lives in the partial's
        keywords (how ResNet builds it).  Anything else is rejected
        loudly rather than silently normalizing with the wrong mode."""
        kw = getattr(self.norm, "keywords", None)
        if kw is None or "use_running_average" not in kw:
            raise TypeError(
                "FusedBottleneckBlock needs `norm` as a functools.partial "
                "of nn.BatchNorm carrying use_running_average (plus "
                f"momentum/epsilon if non-default); got {self.norm!r}")
        # absent knobs fall back to nn.BatchNorm's own defaults so the
        # fused BNs and the norm_proj (instantiated from the same
        # partial) can never diverge
        return (bool(kw["use_running_average"]),
                float(kw.get("momentum", nn.BatchNorm.momentum)),
                float(kw.get("epsilon", nn.BatchNorm.epsilon)))

    def _bn_params(self, name, C, zero_scale=False):
        scale = self.param(
            f"{name}_scale",
            nn.initializers.zeros_init() if zero_scale
            else nn.initializers.ones_init(), (C,), jnp.float32)
        bias = self.param(f"{name}_bias", nn.initializers.zeros_init(),
                          (C,), jnp.float32)
        ra_mean = self.variable("batch_stats", f"{name}_mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", f"{name}_var",
                               lambda: jnp.ones((C,), jnp.float32))
        return scale, bias, ra_mean, ra_var

    def _update_ra(self, ra_mean, ra_var, mean, var, momentum):
        if not self.is_initializing():
            ra_mean.value = momentum * ra_mean.value + (1 - momentum) * mean
            ra_var.value = momentum * ra_var.value + (1 - momentum) * var

    @nn.compact
    def __call__(self, x):
        from ..ops.conv_bn import bn_relu_matmul_stats_t, matmul_bn_stats_t

        use_ra, momentum, eps = self._norm_config()
        dtype = x.dtype
        C_in = x.shape[-1]
        f, f4 = self.filters, self.filters * 4
        init = nn.initializers.lecun_normal()
        w1 = self.param("conv1_kernel", init, (C_in, f), jnp.float32)
        g1, b1, ra1m, ra1v = self._bn_params("bn1", f)
        g2, b2, ra2m, ra2v = self._bn_params("bn2", f)
        w3 = self.param("conv3_kernel", init, (f, f4), jnp.float32)
        g3, b3, ra3m, ra3v = self._bn_params("bn3", f4, zero_scale=True)

        def norm_act(y, mean, var, g, b, act=True):
            out = (y.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
            out = out * g + b
            return (jnp.maximum(out, 0.0) if act else out).astype(dtype)

        residual = x
        B, H, W, _ = x.shape
        x2 = x.reshape(B * H * W, C_in).astype(dtype)
        w1c, w3c = w1.astype(dtype), w3.astype(dtype)
        # pallas only on the real train path (init and eval take the plain
        # XLA composition with the very same parameters)
        fused = not (use_ra or self.is_initializing() or self.force_xla)
        interpret = jax.default_backend() != "tpu"

        if fused:
            y1, m1, v1 = matmul_bn_stats_t(x2, w1c, interpret)
            self._update_ra(ra1m, ra1v, m1, v1, momentum)
        else:
            y1 = x2 @ w1c
            if use_ra:
                m1, v1 = ra1m.value, ra1v.value
            else:
                m1 = jnp.mean(y1.astype(jnp.float32), axis=0)
                v1 = jnp.var(y1.astype(jnp.float32), axis=0)
                self._update_ra(ra1m, ra1v, m1, v1, momentum)
        z1 = norm_act(y1, m1, v1, g1, b1).reshape(B, H, W, f)

        y2 = self.conv(f, (3, 3), self.strides)(z1)
        B2, H2, W2 = y2.shape[:3]
        y2f = y2.reshape(B2 * H2 * W2, f)
        if use_ra:
            m2, v2 = ra2m.value, ra2v.value
        else:
            m2 = jnp.mean(y2f.astype(jnp.float32), axis=0)
            v2 = jnp.var(y2f.astype(jnp.float32), axis=0)
            self._update_ra(ra2m, ra2v, m2, v2, momentum)

        if fused:
            y3, m3, v3 = bn_relu_matmul_stats_t(y2f, m2, v2, g2, b2, w3c,
                                                eps, interpret)
            self._update_ra(ra3m, ra3v, m3, v3, momentum)
        else:
            y3 = norm_act(y2f, m2, v2, g2, b2) @ w3c
            if use_ra:
                m3, v3 = ra3m.value, ra3v.value
            else:
                m3 = jnp.mean(y3.astype(jnp.float32), axis=0)
                v3 = jnp.var(y3.astype(jnp.float32), axis=0)
                self._update_ra(ra3m, ra3v, m3, v3, momentum)
        y = norm_act(y3, m3, v3, g3, b3, act=False)
        y = y.reshape(B2, H2, W2, f4)

        if residual.shape != y.shape:
            residual = self.conv(f4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 block (ResNet-18/34)."""
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """NHWC ResNet with configurable stage sizes.

    ``dtype`` controls activation/compute precision (bfloat16 recommended on
    TPU); parameters and batch statistics stay float32.
    """
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    act: Callable = nn.relu
    # Per-stage fusion gate for pallas-fused block classes, in the
    # conventional ResNet stage naming (2..5 = conv2_x..conv5_x, the
    # names scripts/conv_bn_probe.py reports).  None = fuse every stage
    # (legacy behavior); e.g. (2, 4) fuses only conv2_x/conv4_x and runs
    # the rest through the plain XLA composition (force_xla=True) —
    # silicon r5: fusion wins 4.79x at 56px and 6.99x at 14px but is
    # neutral at 7px, so the optimum is a mix, not all-or-nothing.
    # Ignored for block classes without a pallas path.
    fused_stages: Optional[Tuple[int, ...]] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32, axis_name=None)

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="norm_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        block_base = (self.block_cls.func
                      if isinstance(self.block_cls, partial) else
                      self.block_cls)
        gateable = getattr(block_base, "contains_pallas", False)
        if gateable and self.fused_stages is not None:
            valid = range(2, len(self.stage_sizes) + 2)
            bad = [s for s in self.fused_stages if s not in valid]
            if bad:
                # a typo'd gate (0-indexed, or out of range) would silently
                # run everything on the XLA path while logging fused=1 —
                # poisoning ablation evidence; fail loudly instead
                raise ValueError(
                    f"fused_stages {bad} outside this model's stage range "
                    f"{list(valid)} (conv2_x..conv{valid[-1]}_x)")
        for i, block_count in enumerate(self.stage_sizes):
            stage_gate = {}
            if (gateable and self.fused_stages is not None
                    and (i + 2) not in self.fused_stages):
                stage_gate = {"force_xla": True}
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=self.act,
                    **stage_gate)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
# ResNet-50 with the fused 1x1-conv+BN bottleneck (the roofline attack;
# bench.py selects it via BLUEFOG_FUSED_CONV_BN=1)
ResNet50Fused = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                        block_cls=FusedBottleneckBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
