"""Model zoo used by the examples, benchmarks and tests."""

__all__ = ["get_model"]


def get_model(name: str):
    """Look up a model constructor by name across the zoo (the reference
    examples use ``getattr(torchvision.models, args.model)``; this is the
    equivalent over `models/`).  Only names each module exports resolve.

    Image classifiers (what `examples/resnet.py` / `examples/benchmark.py`
    construct with ``num_classes=``/``dtype=``): ResNet18/34/50/101/152,
    ViT_S16/B16, LeNet.  Other families (TransformerLM, MLP,
    LogisticRegression) resolve too but take their own constructor
    arguments — use them from their dedicated examples/tests.
    """
    from . import resnet, vit, transformer, mlp, lenet
    for mod in (resnet, vit, transformer, mlp, lenet):
        if name in getattr(mod, "__all__", ()):
            return getattr(mod, name)
    raise ValueError(f"unknown model {name!r}")
