"""Model zoo used by the examples, benchmarks and tests."""
