"""LeNet-5 for MNIST (reference parity: examples/pytorch_mnist.py's Net)."""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["LeNet"]


class LeNet(nn.Module):
    """Conv(20) -> pool -> Conv(50) -> pool -> Dense(500) -> Dense(10),
    matching the reference example's architecture shape-for-shape (NHWC)."""
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(20, (5, 5), dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(50, (5, 5), dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(500, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
