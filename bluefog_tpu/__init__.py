"""bluefog_tpu — TPU-native decentralized training framework.

Brand-new JAX/XLA implementation of the BlueFog capability set (reference:
``bluefog`` @ /root/reference), built as single-program SPMD over a TPU ICI
mesh instead of MPI processes.  This top-level module currently exposes:
weighted neighbor averaging over virtual graph topologies (static and
dynamic per-step one-peer schedules), global allreduce/broadcast/allgather,
hierarchical intra/inter-machine averaging, and pairwise gossip; the window
subsystem (``ops/windows.py``) and optimizer wrappers (``optim/``) extend
this surface as they land.

Typical use mirrors the reference (``bluefog/torch/__init__.py:35-107``):

    import bluefog_tpu as bf
    bf.init(bf.topology_util.RingGraph)
    y = bf.neighbor_allreduce(x)     # x: [bf.size(), ...] global view
"""

import os as _os

import jax as _jax

from . import _compat as _compat_mod

_compat_mod.install()

# Honor an explicit JAX_PLATFORMS=cpu request even when a site customization
# has pinned the platform config (which silently overrides the env var):
# re-assert it before any backend exists.  Critical for the virtual CPU mesh
# workflow (tests/launchers export JAX_PLATFORMS=cpu + xla_force_host_
# platform_device_count — SURVEY.md §4 TPU translation note).  Only a
# cpu-containing value is honored: non-cpu values are the site's own default
# (re-asserting those would undo a test harness's config pin).
_env_platforms = _os.environ.get("JAX_PLATFORMS", "")
if "cpu" in _env_platforms.split(","):
    try:
        _jax.config.update("jax_platforms", _env_platforms)
    except Exception:  # backends already initialized — too late, leave as-is
        pass
del _os, _jax, _env_platforms

from . import context as _context
from . import service
from .context import BlueFogContext, init, shutdown, is_initialized
from .utils import blog

from .parallel import topology as topology_util
from .parallel import dynamic as dynamic_topology
from .parallel.topology import (
    ExponentialTwoGraph, ExponentialGraph, SymmetricExponentialGraph,
    MeshGrid2DGraph, StarGraph, RingGraph, FullyConnectedGraph,
    IsTopologyEquivalent, IsRegularGraph, isPowerOf,
    GetRecvWeights, GetSendWeights,
)
from .parallel.dynamic import (
    GetDynamicOnePeerSendRecvRanks,
    GetExp2DynamicSendRecvMachineRanks,
    GetInnerOuterRingDynamicSendRecvRanks,
    GetInnerOuterExpo2DynamicSendRecvRanks,
)
from .parallel.infer import (
    InferSourceFromDestinationRanks,
    InferDestinationFromSourceRanks,
)
from .parallel.schedule import (
    CompiledTopology, DynamicSchedule,
    compile_topology, compile_weight_matrix,
    compile_dynamic_schedule, compile_dynamic_matrices,
)

from .ops.api import (
    allreduce, allreduce_nonblocking, allreduce_, allreduce_nonblocking_,
    broadcast, broadcast_nonblocking, broadcast_, broadcast_nonblocking_,
    allgather, allgather_nonblocking,
    neighbor_allreduce, neighbor_allreduce_nonblocking,
    neighbor_allgather, neighbor_allgather_nonblocking,
    hierarchical_neighbor_allreduce, hierarchical_neighbor_allreduce_nonblocking,
    pair_gossip, pair_gossip_nonblocking,
    barrier, poll, synchronize, wait,
    to_global, from_global, rank_sharding,
    set_weights_override, clear_weights_override, weights_override,
)

from . import async_train
from . import checkpoint
from . import compress
from . import control
from . import fleet
from . import resilience
from . import serving
from .fleet import FleetBootstrapError, FleetSpec  # noqa: F401

from .ops.ring_attention import (
    attention, ring_attention, ulysses_attention,
)

from .ops.windows import (
    win_create, win_free, win_update, win_update_then_collect,
    win_put, win_put_nonblocking, win_get, win_get_nonblocking,
    win_accumulate, win_accumulate_nonblocking,
    win_poll, win_wait, win_flush, win_mutex, win_lock, win_fetch,
    win_publish, win_bootstrap_rank,
    get_current_created_window_names, get_win_version,
    win_version_vector,
    win_associated_p, turn_on_win_ops_with_associated_p,
    turn_off_win_ops_with_associated_p,
    win_state_dict, load_win_state_dict,
)

from .utils.utility import (
    broadcast_parameters, allreduce_parameters, broadcast_optimizer_state,
    deprecated_function_arg, check_extension,
)

from .grad import (
    distributed_value_and_grad, distributed_grad,
    DistributedGradientTape, DistributedOptimizer, broadcast_variables,
)

from .timeline import (
    timeline_start, timeline_end, timeline_enabled,
    timeline_start_activity, timeline_end_activity, timeline_context,
)

from .optim import (
    CommunicationType,
    DistributedGradientAllreduceOptimizer,
    DistributedAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedAdaptThenCombineOptimizer,
    DistributedAdaptWithCombineOptimizer,
    DistributedExactDiffusionOptimizer,
    DistributedWinPutOptimizer,
    DistributedPullGetOptimizer,
    DistributedPushSumOptimizer,
)

from .version import __version__


# -- context delegation (reference basics.py surface) -----------------------

def _delegate(name):
    def fn(*args, **kwargs):
        return getattr(_context.ctx(), name)(*args, **kwargs)
    fn.__name__ = name
    return fn


def size() -> int:
    return _context.ctx().size


def local_size() -> int:
    return _context.ctx().local_size


def machine_size() -> int:
    return _context.ctx().machine_size


rank = _delegate("rank")
local_rank = _delegate("local_rank")
machine_rank = _delegate("machine_rank")
is_homogeneous = _delegate("is_homogeneous")
set_topology = _delegate("set_topology")
set_machine_topology = _delegate("set_machine_topology")
load_topology = _delegate("load_topology")
load_machine_topology = _delegate("load_machine_topology")
is_topo_weighted = _delegate("is_topo_weighted")
is_machine_topo_weighted = _delegate("is_machine_topo_weighted")
in_neighbor_ranks = _delegate("in_neighbor_ranks")
out_neighbor_ranks = _delegate("out_neighbor_ranks")
in_neighbor_machine_ranks = _delegate("in_neighbor_machine_ranks")
out_neighbor_machine_ranks = _delegate("out_neighbor_machine_ranks")
suspend = _delegate("suspend")
resume = _delegate("resume")


# Compatibility toggles that are meaningless without a negotiation stage
# (reference operations.cc:2068-2090) — kept as documented no-ops.
_skip_negotiate = [False]


def set_skip_negotiate_stage(value: bool) -> None:
    _skip_negotiate[0] = bool(value)


def get_skip_negotiate_stage() -> bool:
    return _skip_negotiate[0]


def nccl_built() -> bool:
    """Reference parity (basics.py:147-169): this build uses XLA collectives
    over ICI/DCN; there is no NCCL."""
    return False


def mpi_threads_supported() -> bool:
    return True


def unified_mpi_window_model_supported() -> bool:
    return True
