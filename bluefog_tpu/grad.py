"""Differentiable-collective layer (reference parity: the TensorFlow
frontend — ``bluefog/tensorflow/mpi_ops.py`` registered gradients at
:95,:163,:204 and ``bluefog/tensorflow/optimizers.py``).

The reference's second framework adapter contributes three things beyond the
torch surface:

1. **Collectives with registered gradients** — ``allreduce``/``broadcast``/
   ``allgather`` usable inside a differentiated graph.  In this framework the
   collective primitives (``ops/collectives.py``) are built from
   ``lax.psum/pmean/ppermute/all_gather``, whose transposes JAX already
   knows: grad-of-allreduce is allreduce-of-grad, grad-of-ppermute is the
   inverse permute, so every op — including ``neighbor_allreduce`` — is
   differentiable by construction.  ``tests/test_grad.py`` pins the closed
   forms (∂/∂x of W·x is Wᵀ·ȳ).

2. **`DistributedGradientTape`** (tensorflow/optimizers.py:186) — compute
   local gradients, then average them across ranks.  The JAX-native shape is
   a functional transform: :func:`distributed_value_and_grad` returns a
   jitted global-view function whose gradient output is already averaged
   (one SPMD program: forward, backward, collective).

3. **`DistributedOptimizer`** (tensorflow/optimizers.py:135) and
   ``broadcast_variables`` (tensorflow/mpi_ops.py:64) — thin aliases of the
   gradient-allreduce optimizer and parameter broadcast.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .context import ctx
from .ops import collectives as C
from .parallel.schedule import DynamicSchedule
from .optim.wrappers import DistributedGradientAllreduceOptimizer
from .utils.utility import broadcast_parameters

__all__ = [
    "distributed_value_and_grad",
    "distributed_grad",
    "DistributedGradientTape",
    "DistributedOptimizer",
    "broadcast_variables",
]


def distributed_value_and_grad(
        loss_fn: Callable,
        communication: str = "allreduce",
        sched: Optional[DynamicSchedule] = None,
        average: bool = True):
    """Build a jitted global-view ``(loss, grads)`` function with the
    cross-rank gradient exchange fused into the program.

    Args:
      loss_fn: ``loss_fn(params, *batch) -> scalar`` on one rank's slice —
        ``params`` leaves and batch elements arrive with the leading rank
        axis stripped, exactly like user code under ``bf.init`` in the
        reference.
      communication: ``"allreduce"`` (DistributedGradientTape semantics,
        tensorflow/optimizers.py:186), ``"neighbor_allreduce"`` (weighted
        neighbor average of gradients over the context topology or ``sched``),
        or ``"empty"`` (local gradients).
      sched: optional compiled dynamic schedule for neighbor mode.
      average: allreduce mean vs sum (reference ``average=True`` default).

    Returns:
      ``fn(params, batch, step=0) -> (loss, grads)`` over global-view pytrees
      ([N, ...] leaves); ``loss`` is the cross-rank mean scalar and ``grads``
      are post-exchange.
    """
    if communication not in ("allreduce", "neighbor_allreduce", "empty"):
        raise ValueError(f"unknown communication mode {communication!r}")
    cache = {}

    def build():
        cx = ctx()
        axis = cx.rank_axis
        topo = None
        if communication == "neighbor_allreduce" and sched is None:
            topo = cx.compiled_topology

        def communicate(g, step_s):
            if communication == "allreduce":
                return C.allreduce(g, axis, average=average)
            if communication == "neighbor_allreduce":
                if sched is not None:
                    return C.dynamic_neighbor_allreduce(g, axis, sched, step_s)
                return C.neighbor_allreduce(g, axis, topo)
            return g

        def wrapper(params, batch, step_idx):
            def shard_fn(p_s, b_s, si):
                p = jax.tree.map(lambda a: a[0], p_s)
                b = jax.tree.map(lambda a: a[0], b_s)
                loss, grads = jax.value_and_grad(loss_fn)(p, *b)
                grads = jax.tree.map(lambda g: communicate(g, si), grads)
                mean_loss = jax.lax.pmean(loss, axis)
                return jax.tree.map(lambda a: a[None], grads), mean_loss

            spec = P(axis)
            grads, loss = jax.shard_map(
                shard_fn, mesh=cx.mesh,
                in_specs=(spec, spec, P()),
                out_specs=(spec, P()),
            )(params, batch, step_idx)
            return loss, grads

        return jax.jit(wrapper)

    def fn(params, batch, step: int = 0):
        if not isinstance(batch, (tuple, list)):
            raise TypeError(
                f"batch must be a tuple of loss_fn arguments, e.g. (x,) or "
                f"(x, y); got {type(batch).__name__}")
        cx = ctx()
        # live objects (not ids) in the key: keeps them from being collected
        # and their ids reused after a shutdown/init cycle
        key = (cx.mesh, cx._compiled, jax.tree.structure(params))
        if key not in cache:
            if len(cache) >= 64:
                cache.clear()
            cache[key] = build()
        return cache[key](params, tuple(batch), jnp.asarray(step, jnp.int32))

    return fn


def distributed_grad(loss_fn, **kwargs):
    """Gradient-only variant of :func:`distributed_value_and_grad`."""
    vg = distributed_value_and_grad(loss_fn, **kwargs)

    def fn(params, batch, step: int = 0):
        return vg(params, batch, step)[1]

    return fn


class DistributedGradientTape:
    """Name-parity wrapper over :func:`distributed_value_and_grad`
    (reference ``bf.DistributedGradientTape``,
    tensorflow/optimizers.py:186-203: wrap the tape so ``.gradient`` returns
    allreduced gradients)."""

    def __init__(self, loss_fn: Callable, communication: str = "allreduce",
                 sched: Optional[DynamicSchedule] = None,
                 average: bool = True):
        self._vg = distributed_value_and_grad(
            loss_fn, communication=communication, sched=sched, average=average)

    def value_and_gradient(self, params, batch, step: int = 0):
        return self._vg(params, batch, step)

    def gradient(self, params, batch, step: int = 0):
        return self._vg(params, batch, step)[1]


def DistributedOptimizer(base, num_steps_per_communication: int = 1):
    """TF-frontend name for the gradient-allreduce optimizer (reference
    tensorflow/optimizers.py:135-184 — identical mechanism to the torch
    ``DistributedGradientAllreduceOptimizer``)."""
    return DistributedGradientAllreduceOptimizer(
        base, num_steps_per_communication=num_steps_per_communication)


def broadcast_variables(variables, root_rank: int = 0):
    """Alias of :func:`broadcast_parameters` (reference
    tensorflow/mpi_ops.py:64-92)."""
    return broadcast_parameters(variables, root_rank=root_rank)
