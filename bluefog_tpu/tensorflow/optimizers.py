"""TensorFlow optimizer helpers over the graded collectives.

Reference parity: ``bluefog/tensorflow/optimizers.py`` —
``broadcast_variables`` (:64), ``DistributedOptimizer`` for legacy
``tf.compat.v1.train.Optimizer`` (:135), ``DistributedGradientTape``
(:186).  Two deliberate upgrades over the reference:

- Keras optimizers are SUPPORTED (the reference raises
  ``NotImplementedError`` for them, optimizers.py:160): the wrapper
  re-classes the instance so ``apply_gradients`` averages gradients
  first — the same dynamic re-classing the torch frontends (both the
  reference's and ours) use.
- One code path serves eager and graph modes: the collectives bridge
  through ``tf.py_function`` (mpi_ops.py), so no ``_executing_eagerly``
  forks are needed.
"""

from typing import Optional

import tensorflow as tf

from ..ops import api as _api
from .mpi_ops import _STAGED_DTYPES, _allreduce_group_sum, broadcast

__all__ = [
    "broadcast_variables", "DistributedOptimizer", "DistributedGradientTape",
]


def broadcast_variables(variables, root_rank: int = 0):
    """Assign every variable its rank-``root_rank`` slice on all ranks
    (reference optimizers.py:64-74; variables are global-view)."""
    for var in variables:
        var.assign(broadcast(tf.convert_to_tensor(var), root_rank))


def _allreduce_grads(grads, device: str = ""):
    """Average each non-None gradient across ranks, overlapped: all K
    collectives dispatch before any synchronizes (one group op, not K
    sequential blocking round-trips)."""
    del device
    idx = [i for i, g in enumerate(grads) if g is not None]
    if not idx:
        return list(grads)
    xs, dts = [], []
    for i in idx:
        g = tf.convert_to_tensor(grads[i])
        dts.append(g.dtype)
        staged = _STAGED_DTYPES.get(g.dtype)
        xs.append(tf.cast(g, staged) if staged is not None else g)
    ys = _allreduce_group_sum(xs)
    n = _api.ctx().size
    out = list(grads)
    for i, y, dt in zip(idx, ys, dts):
        r = y / tf.cast(n, y.dtype)
        out[i] = tf.cast(r, dt) if r.dtype != dt else r
    return out


try:
    _LegacyOptimizer = tf.compat.v1.train.Optimizer
except AttributeError:          # future TF without the compat shim
    _LegacyOptimizer = None


if _LegacyOptimizer is not None:
    class _DistributedLegacyOptimizer(_LegacyOptimizer):
        """Wraps a ``tf.compat.v1.train.Optimizer``: ``compute_gradients``
        returns allreduce-averaged gradients (reference :88-135)."""

        def __init__(self, optimizer, name=None, use_locking=False,
                     device=""):
            if name is None:
                name = "Distributed{}".format(type(optimizer).__name__)
            super().__init__(name=name, use_locking=use_locking)
            self._optimizer = optimizer
            self._device = device

        def compute_gradients(self, *args, **kwargs):
            gradients = self._optimizer.compute_gradients(*args, **kwargs)
            grads, vars_ = zip(*gradients)
            return list(zip(_allreduce_grads(grads, self._device), vars_))

        def apply_gradients(self, *args, **kwargs):
            return self._optimizer.apply_gradients(*args, **kwargs)

        def get_slot(self, *args, **kwargs):
            return self._optimizer.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._optimizer.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._optimizer.variables(*args, **kwargs)


class _DistributedKerasMixin:
    """``apply_gradients`` averages gradients across ranks first."""

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        grads, vars_ = zip(*list(grads_and_vars))
        averaged = _allreduce_grads(grads, getattr(self, "_bf_device", ""))
        return super().apply_gradients(
            list(zip(averaged, vars_)), *args, **kwargs)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         use_locking: bool = False, device: str = ""):
    """Wrap an optimizer so gradients are averaged across ranks before
    being applied (reference optimizers.py:135-165).

    Accepts a legacy ``tf.compat.v1.train.Optimizer`` (wrapped exactly like
    the reference) or any Keras optimizer exposing ``apply_gradients``
    (re-classed in place — beyond the reference, which rejects Keras).
    """
    if _LegacyOptimizer is not None and isinstance(optimizer,
                                                   _LegacyOptimizer):
        return _DistributedLegacyOptimizer(optimizer, name, use_locking,
                                           device)
    if hasattr(optimizer, "apply_gradients"):
        cls = type("Distributed" + type(optimizer).__name__,
                   (_DistributedKerasMixin, type(optimizer)), {})
        optimizer.__class__ = cls
        optimizer._bf_device = device
        return optimizer
    raise ValueError(
        "Provided optimizer is neither a legacy TensorFlow optimizer nor "
        "exposes apply_gradients: %s" % optimizer)


class _DistributedGradientTape(tf.GradientTape):
    def gradient(self, target, sources, *args, **kwargs):
        # forward the full tf.GradientTape.gradient contract
        # (output_gradients, unconnected_gradients, nested sources) —
        # tf.nest handles any source structure, None leaves included
        gradients = super().gradient(target, sources, *args, **kwargs)
        flat = _allreduce_grads(tf.nest.flatten(gradients), self._bf_device)
        return tf.nest.pack_sequence_as(gradients, flat)


def DistributedGradientTape(gradtape: tf.GradientTape,
                            device: str = "") -> tf.GradientTape:
    """Re-class an existing ``tf.GradientTape`` so ``gradient()`` returns
    allreduce-averaged gradients (reference optimizers.py:186-203)."""
    cls = type(type(gradtape).__name__,
               (_DistributedGradientTape, type(gradtape)), {})
    gradtape.__class__ = cls
    gradtape._bf_device = device
    return gradtape
