"""TensorFlow-tensor collectives with registered gradients.

Reference parity: ``bluefog/tensorflow/mpi_ops.py`` — allreduce (:108),
broadcast (:141), allgather (:180) and their three registered gradients
(:95, :163, :204).  The reference registers pullbacks on TF custom kernels;
here each op is a ``tf.custom_gradient`` whose forward runs the real JAX
SPMD collective (``ops/api.py``) through a ``tf.py_function`` bridge, so
the ops compose with eager tapes AND inside ``tf.function`` graphs.

Global-view semantics (see package docstring): tensors carry a leading
``size()`` dim.  The reference's per-rank gradient rules translate row-wise:

- allreduce-sum: ``grad_in[i] = sum_j grad_out[j]``   (= allreduce of grad,
  reference :95-107)
- broadcast(root): ``grad_in[root] = sum_j grad_out[j]``, zero elsewhere
  (reference :163-178)
- allgather: ``grad_in[i] = (sum_j grad_out[j])[i*k:(i+1)*k]`` — allreduce
  then take the rank's slice (reference :204-226)

bfloat16/float16 stage through float32 outside the bridge, mirroring the
torch frontend's staging of the reference fp16 path
(``bluefog/common/half.cc``).
"""

from typing import Callable, Optional

import numpy as np
import tensorflow as tf

from ..ops import api as _api

__all__ = ["allreduce", "broadcast", "allgather"]

_STAGED_DTYPES = {tf.bfloat16: tf.float32, tf.float16: tf.float32}


def _bridge(np_fn: Callable[[np.ndarray], np.ndarray], x: tf.Tensor,
            out_shape) -> tf.Tensor:
    """Run a numpy→numpy collective on a tf tensor, eager or in-graph.

    ``tf.py_function`` executes immediately under eager and becomes a host
    op inside ``tf.function`` — one uniform path for both modes (the
    reference needs separate eager/graph branches, optimizers.py:33-41).
    ``py_function`` erases static shapes, so the caller supplies them.
    """
    def call(a):
        return np.asarray(np_fn(a.numpy()), dtype=x.dtype.as_numpy_dtype)

    out = tf.py_function(call, [x], Tout=x.dtype)
    out.set_shape(out_shape)
    return out


def _dispatch(compute: Callable[[tf.Tensor], tf.Tensor], t) -> tf.Tensor:
    """Common wrapper: convert input, stage sub-float32 dtypes, and restore
    the input dtype on the way out — like the torch frontend's
    ``synchronize`` (averaging an int tensor yields its truncated-int
    average there, not a silent float64 upcast from TF's true division)."""
    t = tf.convert_to_tensor(t)
    staged = _STAGED_DTYPES.get(t.dtype)
    x = tf.cast(t, staged) if staged is not None else t
    out = compute(x)
    return tf.cast(out, t.dtype) if out.dtype != t.dtype else out


def _group_bridge(xs) -> list:
    """One ``py_function`` carrying K tensors through K *overlapped*
    allreduces: dispatch every collective nonblocking, then synchronize —
    K round-trips become one dispatch wave (the optimizer/tape path calls
    this with one gradient per variable)."""
    dts = [x.dtype for x in xs]

    def call(*arrays):
        handles = [_api.allreduce_nonblocking(a.numpy(), False)
                   for a in arrays]
        return [np.asarray(_api.synchronize(h), dtype=d.as_numpy_dtype)
                for h, d in zip(handles, dts)]

    outs = tf.py_function(call, list(xs), Tout=dts)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    for o, x in zip(outs, xs):
        o.set_shape(x.shape)
    return list(outs)


def _allreduce_group_sum(xs):
    """Graded group allreduce-sum: gradient of a group sum is the group sum
    of the gradients (the per-tensor rule, applied in one wave)."""

    @tf.custom_gradient
    def fn(*vs):
        ys = _group_bridge(vs)

        def grad(*dys):
            return tuple(_group_bridge(
                [tf.convert_to_tensor(d) for d in dys]))

        return tuple(ys), grad

    return list(fn(*xs))


def _allreduce_sum(x: tf.Tensor, name: Optional[str]) -> tf.Tensor:
    @tf.custom_gradient
    def fn(v):
        y = _bridge(lambda a: _api.allreduce(a, False, name), v, v.shape)

        def grad(dy):
            return _bridge(lambda a: _api.allreduce(a, False, name), dy,
                           dy.shape)

        return y, grad

    return fn(x)


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              device: str = "") -> tf.Tensor:
    """Allreduce of the per-rank slices (reference mpi_ops.py:108-138).

    ``average=True`` divides the sum by ``size()`` as a separate TF op so
    autodiff chains through it exactly like the reference's graph
    (sum-op with registered gradient, then a division).  ``device`` is
    accepted for signature parity; placement is the mesh's concern here.
    """
    del device

    def compute(x):
        summed = _allreduce_sum(x, name)
        if not average:
            return summed
        return summed / tf.cast(_api.ctx().size, x.dtype)

    return _dispatch(compute, tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None) -> tf.Tensor:
    """Replicate rank ``root_rank``'s slice to all ranks (reference
    mpi_ops.py:141-161; gradient :163-178)."""
    root = int(root_rank)

    def compute(x):
        @tf.custom_gradient
        def fn(v):
            y = _bridge(lambda a: _api.broadcast(a, root, name), v, v.shape)

            def grad(dy):
                def g_np(a):
                    s = np.asarray(_api.allreduce(a, False, name))
                    out = np.zeros_like(s)
                    out[root] = s[root]
                    return out

                return _bridge(g_np, dy, dy.shape)

            return y, grad

        return fn(x)

    return _dispatch(compute, tensor)


def _ragged_allgather(parts, name: Optional[str]) -> tf.Tensor:
    """Variable-size allgather on a list of per-rank tf tensors (the
    reference op's allgatherv behavior — its gradient allgathers the
    first dims to split, :204-226; here the counts are static)."""
    n = _api.ctx().size
    if len(parts) != n:
        raise ValueError(f"ragged input must list one tensor per rank ({n}), "
                         f"got {len(parts)}")
    xs = [tf.convert_to_tensor(p) for p in parts]
    in_dtype = xs[0].dtype
    if any(x.dtype != in_dtype for x in xs):
        raise ValueError(
            f"ragged input mixes tf dtypes "
            f"{sorted({x.dtype.name for x in xs})}; cast to one dtype first")
    staged = _STAGED_DTYPES.get(in_dtype)
    if staged is not None:
        # same f32 staging contract as every other op here; the tf.cast
        # pair also keeps the gradient chain in f32
        out = _ragged_allgather([tf.cast(x, staged) for x in xs], name)
        return tf.cast(out, in_dtype)
    if any(x.shape[0] is None for x in xs):
        raise ValueError(
            "variable-size allgather needs statically known first dims "
            "(the ragged layout is compiled into the program); got a None "
            "leading dim — avoid unknown-shape input_signatures here")
    counts = [int(x.shape[0]) for x in xs]
    offsets = np.concatenate([[0], np.cumsum(counts)])
    total = int(offsets[-1])
    out_shape = tf.TensorShape([n, total]).concatenate(xs[0].shape[1:])

    @tf.custom_gradient
    def fn(*vs):
        def call(*arrays):
            out = _api.allgather([a.numpy() for a in arrays], name)
            return np.asarray(out, dtype=vs[0].dtype.as_numpy_dtype)

        y = tf.py_function(call, list(vs), Tout=vs[0].dtype)
        y.set_shape(out_shape)

        def grad(dy):
            def g_np(a):
                s = np.asarray(_api.allreduce(a, False, name))
                return [s[i, offsets[i]:offsets[i + 1]] for i in range(n)]

            gs = tf.py_function(g_np, [dy], Tout=[dy.dtype] * n)
            for g, v in zip(gs, vs):
                g.set_shape(v.shape)
            return tuple(gs)

        return y, grad

    return fn(*xs)


def allgather(tensor, name: Optional[str] = None) -> tf.Tensor:
    """Concatenate all ranks' slices along dim 0: every rank's result slice
    is ``concat_i x[i]`` (reference mpi_ops.py:180-201; gradient
    :204-226).  A LIST of per-rank tensors with differing first dims runs
    the variable-size form (exact ragged concat, ``[size, sum(counts), …]``)."""
    if isinstance(tensor, (list, tuple)):
        return _ragged_allgather(list(tensor), name)

    def compute(x):
        n = _api.ctx().size
        out_shape = tf.TensorShape(
            [x.shape[0], None if x.shape[1] is None else n * x.shape[1]]
        ).concatenate(x.shape[2:])

        @tf.custom_gradient
        def fn(v):
            y = _bridge(lambda a: _api.allgather(a, name), v, out_shape)

            def grad(dy):
                def g_np(a):
                    s = np.asarray(_api.allreduce(a, False, name))
                    k = s.shape[1] // n
                    return np.stack(
                        [s[i, i * k:(i + 1) * k] for i in range(n)])

                return _bridge(g_np, dy, v.shape)

            return y, grad

        return fn(x)

    return _dispatch(compute, tensor)
