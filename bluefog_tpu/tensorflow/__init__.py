"""TensorFlow frontend: tf.Tensor collectives over the JAX mesh.

Reference parity: ``bluefog/tensorflow/__init__.py`` — the second
framework adapter exposes init/shutdown, the rank/size/topology queries,
the three gradient-registered collectives (allreduce/broadcast/allgather,
``bluefog/tensorflow/mpi_ops.py:84-226``), and the optimizer helpers
(``DistributedOptimizer``, ``DistributedGradientTape``,
``broadcast_variables`` — ``bluefog/tensorflow/optimizers.py``).

Like the torch frontend (``bluefog_tpu/torch``), tensors are global-view:
leading dim == ``size()``, rank i's tensor is slice ``i``, and every op
executes the same SPMD shard_map program the JAX API runs.  The JAX-native
equivalents of the TF components (functional transforms instead of tapes)
live in ``bluefog_tpu.grad``; this package is for code that holds actual
``tf.Tensor``/``tf.Variable`` objects.
"""

from .. import (
    init,
    shutdown,
    size,
    local_size,
    rank,
    local_rank,
    load_topology,
    set_topology,
    in_neighbor_ranks,
    out_neighbor_ranks,
    mpi_threads_supported,
    unified_mpi_window_model_supported,
    check_extension,
)

from .mpi_ops import allreduce, broadcast, allgather

from .optimizers import (
    broadcast_variables,
    DistributedOptimizer,
    DistributedGradientTape,
)

__all__ = [
    "init", "shutdown", "size", "local_size", "rank", "local_rank",
    "load_topology", "set_topology",
    "in_neighbor_ranks", "out_neighbor_ranks",
    "mpi_threads_supported", "unified_mpi_window_model_supported",
    "check_extension",
    "allreduce", "broadcast", "allgather",
    "broadcast_variables", "DistributedOptimizer", "DistributedGradientTape",
]
