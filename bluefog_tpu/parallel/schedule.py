"""Compile virtual topologies into TPU collective schedules.

A BlueFog topology is a weighted digraph over ranks.  On MPI the reference
materializes it as an ``MPI_Dist_graph`` communicator and moves every edge
with point-to-point sends (``bluefog/common/mpi_controller.cc:419-517``).  On
a TPU mesh the natural execution is by *circulant decomposition*: group the
edges by ring offset ``d = (dst - src) % size``; each offset becomes one
``jax.lax.ppermute`` over the mesh axis (riding ICI), and the weighted sum of
the permuted values reproduces the mixing matrix exactly.  Sparse graphs
(exp2: log2 N offsets, ring: 2, mesh-grid: 4ish) therefore cost only a few
permutes, and XLA overlaps them with compute.

Dynamic (per-step) topologies compile to a *fixed* superset of offsets with
step-indexed weight tables, so the jitted program never changes shape and no
recompilation happens when the graph hops (SURVEY.md §7 hard part 2).
"""

from dataclasses import dataclass, field
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import networkx as nx

from . import dynamic as dynamic_mod

__all__ = [
    "Shift",
    "CompiledTopology",
    "compile_topology",
    "compile_weight_matrix",
    "DynamicSchedule",
    "compile_dynamic_schedule",
]


@dataclass(frozen=True, eq=False)
class Shift:
    """One circulant component of a topology.

    ``pairs`` lists the real (src, dst) device pairs for this offset — ranks
    not named as a destination receive zeros from ppermute, and their weight
    is zero, so partial offsets are safe.
    ``recv_weights[i]`` is the factor rank i applies to the value arriving
    over this offset; ``send_weights[i]`` the factor rank i applies before
    sending (used by dst-weighted mode; 1.0 otherwise).
    """
    offset: int
    pairs: Tuple[Tuple[int, int], ...]
    recv_weights: np.ndarray
    send_weights: np.ndarray


@dataclass(frozen=True, eq=False)
class CompiledTopology:
    """Execution plan for one static topology on a 1-D mesh axis."""
    size: int
    self_weights: np.ndarray          # [N]; A[i, i]
    shifts: Tuple[Shift, ...]
    weight_matrix: np.ndarray         # [N, N]; W[i, j] = j's weight for i's value
    digraph: Optional[nx.DiGraph] = field(default=None)

    @property
    def offsets(self) -> Tuple[int, ...]:
        return tuple(s.offset for s in self.shifts)

    def in_neighbor_ranks(self, rank: int) -> List[int]:
        srcs = np.nonzero(self.weight_matrix[:, rank])[0]
        return [int(s) for s in srcs if s != rank]

    def out_neighbor_ranks(self, rank: int) -> List[int]:
        dsts = np.nonzero(self.weight_matrix[rank, :])[0]
        return [int(d) for d in dsts if d != rank]

    def in_degrees(self) -> np.ndarray:
        off_diag = self.weight_matrix.copy()
        np.fill_diagonal(off_diag, 0.0)
        return (off_diag != 0).sum(axis=0)

    @property
    def is_regular(self) -> bool:
        degs = self.in_degrees()
        return bool((degs == degs[0]).all())


def compile_weight_matrix(W: np.ndarray,
                          digraph: Optional[nx.DiGraph] = None) -> CompiledTopology:
    """Compile a mixing matrix (``W[i, j]`` = weight of i's value at j).

    Every nonzero off-diagonal entry becomes a member of its offset's
    ppermute; zero entries cost nothing.
    """
    W = np.asarray(W, dtype=np.float64)
    n = W.shape[0]
    if W.shape != (n, n):
        raise ValueError(f"weight matrix must be square, got {W.shape}")

    shifts = []
    srcs, dsts = np.nonzero(W)
    by_offset = {}
    for s, d in zip(srcs, dsts):
        if s == d:
            continue
        by_offset.setdefault(int((d - s) % n), []).append((int(s), int(d)))
    for offset in sorted(by_offset):
        pairs = tuple(sorted(by_offset[offset]))
        recv = np.zeros(n)
        for s, d in pairs:
            recv[d] = W[s, d]
        shifts.append(Shift(offset=offset, pairs=pairs,
                            recv_weights=recv, send_weights=np.ones(n)))
    return CompiledTopology(
        size=n,
        self_weights=np.diag(W).copy(),
        shifts=tuple(shifts),
        weight_matrix=W,
        digraph=digraph,
    )


def compile_topology(topo: nx.DiGraph) -> CompiledTopology:
    """Compile a weighted ``networkx.DiGraph`` (BlueFog convention)."""
    return compile_weight_matrix(nx.to_numpy_array(topo), digraph=topo)


# ---------------------------------------------------------------------------
# Dynamic schedules
# ---------------------------------------------------------------------------

@dataclass(frozen=True, eq=False)
class DynamicSchedule:
    """A periodic per-step topology, compiled to fixed shape.

    The jitted collective receives the *step index* as data and gathers that
    step's weights from the tables below; the offset set never changes, so
    the XLA program is compiled once.

    Attributes:
      size: number of ranks.
      period: schedule period T (tables repeat after T steps).
      offsets: static tuple of ring offsets used by any step.
      self_weights: [T, N] self weight per step per rank.
      recv_weights: [T, n_offsets, N] weight rank i applies to data arriving
        over offsets[k] at step t (zero when no such edge).
      matrices: [T, N, N] the per-step mixing matrices (for reference/tests).
    """
    size: int
    period: int
    offsets: Tuple[int, ...]
    self_weights: np.ndarray
    recv_weights: np.ndarray
    matrices: np.ndarray


def compile_dynamic_schedule(
        factory: Callable[[int], Iterator[Tuple[List[int], List[int]]]],
        size: int,
        period: Optional[int] = None,
        max_period: int = 4096) -> DynamicSchedule:
    """Compile a per-rank generator family into a :class:`DynamicSchedule`.

    ``factory(rank)`` yields ``(send_ranks, recv_ranks)`` as in the reference
    generators; weights follow the one-peer convention ``1/(in_degree + 1)``.
    """
    if period is None:
        period = dynamic_mod.schedule_period(factory, size, max_period=max_period)
    mats = dynamic_mod.dynamic_mixing_matrices(factory, size, period)
    return compile_dynamic_matrices(mats)


def compile_dynamic_matrices(mats: np.ndarray) -> DynamicSchedule:
    """Compile a [T, N, N] stack of per-step mixing matrices."""
    mats = np.asarray(mats, dtype=np.float64)
    T, n, _ = mats.shape

    offsets = sorted({
        int((d - s) % n)
        for t in range(T)
        for s, d in zip(*np.nonzero(mats[t]))
        if s != d
    })
    offset_index = {off: k for k, off in enumerate(offsets)}

    self_w = np.stack([np.diag(mats[t]) for t in range(T)])
    recv_w = np.zeros((T, len(offsets), n))
    for t in range(T):
        srcs, dsts = np.nonzero(mats[t])
        for s, d in zip(srcs, dsts):
            if s == d:
                continue
            recv_w[t, offset_index[int((d - s) % n)], d] = mats[t][s, d]
    return DynamicSchedule(
        size=n,
        period=T,
        offsets=tuple(offsets),
        self_weights=self_w,
        recv_weights=recv_w,
        matrices=mats,
    )
