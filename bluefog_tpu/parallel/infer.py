"""Collectively infer reverse edges of a (dynamic) topology.

Reference parity: ``bluefog/torch/topology_util.py:22-108``
(``InferSourceFromDestinationRanks`` / ``InferDestinationFromSourceRanks``).
There every MPI rank contributes its own neighbor list and an allgatherv
assembles the global adjacency.  In this framework one controller drives the
whole mesh (global view), so the caller passes *all* ranks' lists at once and
receives all ranks' inferred lists back; the cross-rank exchange the reference
performs over MPI is pure host metadata here.  When the context is live the
implementation still routes the degree table through the device ``allgather``
(padded to uniform shape — SPMD needs static shapes) so the collective code
path is exercised exactly like the reference's.

The adjacency-matrix construction reproduces the reference's normalization
formula verbatim: ``W_out[i, j] = W[i, j] / sum_k W[j, k]`` with ``W = I +
adjacency`` (reference topology_util.py:103-108) — column-normalized for
regular graphs.
"""

import collections
from typing import List, Sequence, Tuple, Union

import numpy as np

__all__ = [
    "InferSourceFromDestinationRanks",
    "InferDestinationFromSourceRanks",
]


def _check_rank_lists(rank_lists: Sequence[Sequence[int]], size: int) -> None:
    if len(rank_lists) != size:
        raise ValueError(
            f"global view requires one rank list per rank: expected {size} "
            f"lists, got {len(rank_lists)}")
    for self_rank, rank_list in enumerate(rank_lists):
        for rank in rank_list:
            if not isinstance(rank, (int, np.integer)):
                raise ValueError(
                    f"rank list of rank {self_rank} contains element that is "
                    f"not integer.")
            if rank < 0 or rank >= size:
                raise ValueError(
                    f"rank list of rank {self_rank} contains element that is "
                    f"not between 0 and size-1.")
        if len(set(rank_list)) != len(rank_list):
            raise ValueError(
                f"rank list of rank {self_rank} contains duplicated elements.")
        if self_rank in rank_list:
            raise ValueError(
                f"rank list of rank {self_rank} contains self rank.")


def _gather_adjacency(rank_lists: Sequence[Sequence[int]],
                      size: int) -> dict:
    """Assemble {rank: sorted neighbor list} — over the device allgather when
    a context is live (mirrors the reference's collective assembly,
    topology_util.py:83-91), host-side otherwise."""
    from .. import context as _ctx_mod

    if _ctx_mod.is_initialized() and _ctx_mod.ctx().size == size:
        from ..ops import api as _api
        max_deg = max((len(r) for r in rank_lists), default=0)
        padded = np.full((size, max(max_deg, 1)), -1, dtype=np.int32)
        for i, r in enumerate(rank_lists):
            padded[i, :len(r)] = sorted(r)
        gathered = np.asarray(_api.allgather(padded[:, None, :]))
        # every rank's slice is the full [size, max_deg] table; decode rank 0's
        table = gathered.reshape(size, size, -1)[0]
        return {i: [int(v) for v in row if v >= 0] for i, row in enumerate(table)}
    return {i: sorted(int(v) for v in r) for i, r in enumerate(rank_lists)}


def _infer_topo(rank_lists: Sequence[Sequence[int]], size: int,
                transpose: bool, construct_adjacency_matrix: bool):
    adjacency_dict = _gather_adjacency(rank_lists, size)

    inv_adjacency_dict = collections.defaultdict(list)
    for k, adj in adjacency_dict.items():
        for v in adj:
            inv_adjacency_dict[v].append(k)
    inferred = [inv_adjacency_dict.get(r, []) for r in range(size)]

    if not construct_adjacency_matrix:
        return inferred

    W = np.eye(size)
    for k, adj in adjacency_dict.items():
        W[k, adj] = 1
    if transpose:
        W = W.T
    return inferred, W / W.sum(axis=1)


def InferSourceFromDestinationRanks(
        dst_ranks: Sequence[Sequence[int]],
        construct_adjacency_matrix: bool = False,
) -> Union[List[List[int]], Tuple[List[List[int]], np.ndarray]]:
    """Infer every rank's source ranks from all ranks' destination lists.

    Args:
      dst_ranks: ``dst_ranks[i]`` is rank i's destination list (global view;
        the reference's per-process call, topology_util.py:22-47, passes only
        the local list and allgathers the rest).
      construct_adjacency_matrix: also return the reference's normalized
        adjacency matrix, where ``w_ij`` is the weight sending from node i to
        node j (column-normalized style).

    Returns:
      ``src_ranks`` — ``src_ranks[i]`` is the sorted-by-construction list of
      ranks that send to i; with ``construct_adjacency_matrix`` a 2-D numpy
      array is returned as well.
    """
    size = len(dst_ranks)
    _check_rank_lists(dst_ranks, size)
    return _infer_topo(dst_ranks, size, transpose=False,
                       construct_adjacency_matrix=construct_adjacency_matrix)


def InferDestinationFromSourceRanks(
        src_ranks: Sequence[Sequence[int]],
        construct_adjacency_matrix: bool = False,
) -> Union[List[List[int]], Tuple[List[List[int]], np.ndarray]]:
    """Infer every rank's destination ranks from all ranks' source lists.

    Mirror of :func:`InferSourceFromDestinationRanks` (reference
    topology_util.py:50-77, ``transpose=True`` branch).
    """
    size = len(src_ranks)
    _check_rank_lists(src_ranks, size)
    return _infer_topo(src_ranks, size, transpose=True,
                       construct_adjacency_matrix=construct_adjacency_matrix)
