"""Schedule IR: the serializable form every exchange schedule lowers from.

A *schedule* here is a periodic sequence of mixing rounds.  Each round is
one column-stochastic matrix, but the IR stores it the way the TPU
executes it — per-round **topology** (the directed edges that actually
move data), the **permute offsets** those edges group into under the
circulant decomposition (``schedule.py``), and the **weight tables**
(edge weights + per-rank self weights).  Three properties make it the
common construction path for every schedule in the repo:

* **serializable** — ``to_json``/``from_json`` round-trip exactly (edge
  weights ride Python floats, which serialize float64 losslessly), so a
  synthesized schedule is an offline artifact the controller can load,
  ``bfctl show --schedule`` can render, and a trail record can
  fingerprint;
* **hashable** — :meth:`ScheduleIR.fingerprint` is a content hash over
  the canonical JSON (name excluded), giving decision trails and caches
  a stable identity for "the same schedule";
* **lowerable** — :func:`compile_schedule_ir` produces the repo's
  :class:`~.schedule.DynamicSchedule` via ``compile_dynamic_matrices``,
  and :meth:`ScheduleIR.permute_budget` predicts EXACTLY how many
  ``ppermute`` ops that lowering traces per step (the offset superset —
  every step pays every offset, absent edges carry zero weight), which
  is what bflint's trace-collective-budget pass checks against the HLO.

The legacy constructions (static W, the one-peer exponential family,
the cost-reweighted W) all build through :func:`ir_from_matrix` /
:func:`ir_from_matrices` / :func:`ir_from_one_peer` — bit-exactness with
the pre-IR hand-built stacks is regression-tested
(``tests/test_schedule_ir.py``).
"""

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import dynamic as _dyn
from .schedule import DynamicSchedule, compile_dynamic_matrices

__all__ = [
    "ScheduleRound", "ScheduleIR",
    "ir_from_matrix", "ir_from_matrices", "ir_from_one_peer",
    "check_matrix_invariants", "check_schedule_invariants",
    "compile_schedule_ir",
]


@dataclasses.dataclass(frozen=True)
class ScheduleRound:
    """One mixing round: directed weighted edges + per-rank self weights.

    ``edges`` is a sorted tuple of ``(src, dst, weight)`` with
    ``src != dst``; ``self_weights[i]`` is the diagonal ``W[i, i]``.
    The matrix convention matches the rest of the repo:
    ``W[i, j]`` = the weight receiver ``j`` applies to ``i``'s value.
    """

    edges: Tuple[Tuple[int, int, float], ...]
    self_weights: Tuple[float, ...]

    def offsets(self, size: int) -> Tuple[int, ...]:
        """The ring offsets this round's edges decompose into."""
        return tuple(sorted({(d - s) % size for s, d, _ in self.edges}))

    def matrix(self, size: int) -> np.ndarray:
        """This round's ``[N, N]`` mixing matrix (float64)."""
        W = np.zeros((size, size), dtype=np.float64)
        W[np.arange(size), np.arange(size)] = self.self_weights
        for s, d, w in self.edges:
            W[s, d] = w
        return W


@dataclasses.dataclass(frozen=True, eq=False)
class ScheduleIR:
    """A periodic exchange schedule as rounds of weighted topologies."""

    size: int
    rounds: Tuple[ScheduleRound, ...]
    name: str = "schedule"

    def __post_init__(self):
        if not self.rounds:
            raise ValueError("a ScheduleIR needs at least one round")
        for r in self.rounds:
            if len(r.self_weights) != self.size:
                raise ValueError(
                    f"round self_weights length {len(r.self_weights)} != "
                    f"size {self.size}")

    # -- identity -----------------------------------------------------------

    def __eq__(self, other) -> bool:
        if not isinstance(other, ScheduleIR):
            return NotImplemented
        return self.fingerprint() == other.fingerprint()

    def __hash__(self) -> int:
        return int(self.fingerprint()[:16], 16)

    def fingerprint(self) -> str:
        """Content hash of (size, rounds) — the schedule's identity.

        The ``name`` is presentation, not content: a renamed schedule
        mixes identically, so it hashes identically."""
        payload = json.dumps(
            {"size": self.size, "rounds": self._rounds_payload()},
            sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(payload.encode()).hexdigest()

    # -- structure ----------------------------------------------------------

    @property
    def period(self) -> int:
        return len(self.rounds)

    def offsets(self) -> Tuple[int, ...]:
        """The offset SUPERSET across all rounds — what the lowered
        program traces every step (``compile_dynamic_matrices`` pays
        every offset each step; absent edges carry zero weight)."""
        offs = set()
        for r in self.rounds:
            offs.update(r.offsets(self.size))
        return tuple(sorted(offs))

    def permute_budget(self, wire_arrays: int = 1) -> int:
        """Traced ``ppermute`` count per step per fusion bucket: one
        permute per superset offset per wire array."""
        return len(self.offsets()) * int(wire_arrays)

    def matrices(self) -> np.ndarray:
        """The ``[T, N, N]`` per-round mixing matrices."""
        return np.stack([r.matrix(self.size) for r in self.rounds])

    def tile(self, period: int) -> np.ndarray:
        """The matrices tiled out to a covering period (for stacking
        modes of different natural periods into one
        ``SwitchableSchedule``)."""
        if period % self.period:
            raise ValueError(
                f"cannot tile period-{self.period} schedule to "
                f"{period} steps (not a multiple)")
        return np.tile(self.matrices(), (period // self.period, 1, 1))

    # -- serialization ------------------------------------------------------

    def _rounds_payload(self) -> List[Dict]:
        return [{"edges": [[s, d, w] for s, d, w in r.edges],
                 "self_weights": list(r.self_weights)}
                for r in self.rounds]

    def asdict(self) -> Dict:
        return {"name": self.name, "size": self.size,
                "rounds": self._rounds_payload()}

    @classmethod
    def fromdict(cls, d: Dict) -> "ScheduleIR":
        rounds = tuple(
            ScheduleRound(
                edges=tuple(sorted((int(s), int(d_), float(w))
                                   for s, d_, w in r["edges"])),
                self_weights=tuple(float(w) for w in r["self_weights"]))
            for r in d["rounds"])
        return cls(size=int(d["size"]), rounds=rounds,
                   name=str(d.get("name", "schedule")))

    def to_json(self) -> str:
        return json.dumps(self.asdict(), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ScheduleIR":
        return cls.fromdict(json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")

    @classmethod
    def load(cls, path: str) -> "ScheduleIR":
        with open(path) as f:
            return cls.from_json(f.read())


# ---------------------------------------------------------------------------
# Constructors: every schedule family in the repo builds through these
# ---------------------------------------------------------------------------

def _round_from_matrix(W: np.ndarray) -> ScheduleRound:
    W = np.asarray(W, dtype=np.float64)
    n = W.shape[0]
    edges = []
    for s, d in zip(*np.nonzero(W)):
        if s != d:
            edges.append((int(s), int(d), float(W[s, d])))
    return ScheduleRound(edges=tuple(sorted(edges)),
                         self_weights=tuple(float(W[i, i]) for i in range(n)))


def ir_from_matrix(W: np.ndarray, name: str = "static") -> ScheduleIR:
    """A single-round (period-1) schedule from one mixing matrix."""
    W = np.asarray(W, dtype=np.float64)
    if W.ndim != 2 or W.shape[0] != W.shape[1]:
        raise ValueError(f"need a square matrix, got shape {W.shape}")
    return ScheduleIR(size=W.shape[0], rounds=(_round_from_matrix(W),),
                      name=name)


def ir_from_matrices(mats: np.ndarray, name: str = "dynamic") -> ScheduleIR:
    """A multi-round schedule from a ``[T, N, N]`` matrix stack."""
    mats = np.asarray(mats, dtype=np.float64)
    if mats.ndim != 3 or mats.shape[1] != mats.shape[2]:
        raise ValueError(f"need a [T, N, N] stack, got shape {mats.shape}")
    return ScheduleIR(
        size=mats.shape[1],
        rounds=tuple(_round_from_matrix(mats[t])
                     for t in range(mats.shape[0])),
        name=name)


def ir_from_one_peer(digraph, period: Optional[int] = None,
                     max_period: int = 4096,
                     name: str = "one_peer") -> ScheduleIR:
    """The O(1)-degree one-peer exponential family over ``digraph``
    (arXiv:2110.13363) — the provably-convergent fallback schedule."""
    size = digraph.number_of_nodes()
    factory = _dyn.one_peer_factory(digraph)
    if period is None:
        period = _dyn.schedule_period(factory, size, max_period=max_period)
    mats = _dyn.dynamic_mixing_matrices(factory, size, period)
    return ir_from_matrices(mats, name=name)


# ---------------------------------------------------------------------------
# Invariants
# ---------------------------------------------------------------------------

def check_matrix_invariants(W: np.ndarray, *,
                            gap_floor: Optional[float] = None,
                            atol: float = 1e-8) -> Dict[str, float]:
    """Validate one mixing matrix against the repo's invariants.

    Raises ``ValueError`` on a violation; returns measured quantities.

    * non-negativity — averaging weights only;
    * column-stochasticity — each receiver's weights sum to 1 (mass
      conservation, the invariant every compiled topology satisfies);
    * spectral-gap floor (optional) — ``1 - |λ₂| >= gap_floor`` so the
      matrix actually contracts consensus distance.
    """
    W = np.asarray(W, dtype=np.float64)
    if W.ndim != 2 or W.shape[0] != W.shape[1]:
        raise ValueError(f"need a square matrix, got shape {W.shape}")
    if (W < -atol).any():
        i, j = np.unravel_index(int(np.argmin(W)), W.shape)
        raise ValueError(
            f"mixing matrix has negative weight W[{i},{j}]={W[i, j]:.3g}")
    col = W.sum(axis=0)
    worst = float(np.abs(col - 1.0).max())
    if worst > atol:
        j = int(np.argmax(np.abs(col - 1.0)))
        raise ValueError(
            f"mixing matrix column {j} sums to {col[j]:.6g} (not "
            f"column-stochastic; worst deviation {worst:.3g})")
    out = {"col_dev": worst}
    if gap_floor is not None:
        from ..resilience.repair import spectral_gap
        gap = float(spectral_gap(W))
        out["spectral_gap"] = gap
        if gap < gap_floor:
            raise ValueError(
                f"spectral gap {gap:.3g} below floor {gap_floor:.3g} — "
                f"the matrix does not contract consensus")
    return out


def check_schedule_invariants(ir: ScheduleIR, *,
                              gap_floor: Optional[float] = None,
                              atol: float = 1e-8) -> Dict[str, float]:
    """Validate every round of a schedule, plus its period-level mixing.

    Each round must be non-negative and column-stochastic.  The
    spectral-gap floor applies to the PERIOD PRODUCT ``W_{T-1}···W_0``:
    a single round of a multi-round schedule need not contract (a
    one-peer round moves mass over one edge family only), but one full
    period must.  The product of column-stochastic matrices is
    column-stochastic, so the same gap measure applies.
    """
    prod = np.eye(ir.size, dtype=np.float64)
    worst_dev = 0.0
    for t, r in enumerate(ir.rounds):
        W = r.matrix(ir.size)
        try:
            stats = check_matrix_invariants(W, gap_floor=None, atol=atol)
        except ValueError as e:
            raise ValueError(f"round {t}: {e}") from None
        worst_dev = max(worst_dev, stats["col_dev"])
        prod = prod @ W
    out = {"col_dev": worst_dev}
    if gap_floor is not None:
        from ..resilience.repair import spectral_gap
        gap = float(spectral_gap(prod))
        out["spectral_gap"] = gap
        if gap < gap_floor:
            raise ValueError(
                f"period-product spectral gap {gap:.3g} below floor "
                f"{gap_floor:.3g} — {ir.period} round(s) do not mix")
    return out


# ---------------------------------------------------------------------------
# Lowering
# ---------------------------------------------------------------------------

def compile_schedule_ir(ir: ScheduleIR) -> DynamicSchedule:
    """Lower an IR to the executable :class:`DynamicSchedule`.

    The lowered program traces ``ir.permute_budget(wire_arrays)``
    ppermutes per step per fusion bucket — the prediction bflint's
    trace-collective-budget pass verifies against the HLO.
    """
    return compile_dynamic_matrices(ir.matrices())
