"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

No reference counterpart (SURVEY.md §2.6: PP absent in BlueFog); built
because layer pipelining is the remaining first-class TPU scaling axis.
Design is the canonical SPMD pipeline: every stage runs the *same* jitted
program (shard_map over a ``pp`` axis), stage ``s`` owns layers
``[s*K, (s+1)*K)`` as a stacked parameter tree sharded on its leading axis,
and activations flow stage-to-stage with one ``lax.ppermute`` per tick
while ``M`` microbatches stream through (``M + S - 1`` ticks total; the
pipeline bubble's garbage outputs are masked out of the loss, so autodiff
sends them zero cotangents and gradients are exact).

Embedding and LM head are computed outside the pipelined stack on every
rank (they are cheap relative to the blocks and this keeps every stage's
program identical — the SPMD requirement).
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["stack_block_params", "unstack_block_params",
           "make_pp_lm_train_step", "pp_mesh"]


def pp_mesh(stages: int, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[:stages])
    if devices.size != stages:
        raise ValueError(f"need {stages} devices, have {devices.size}")
    return Mesh(devices.reshape(stages), ("pp",))


def stack_block_params(params, num_layers: int):
    """Split a Transformer params tree into (stacked blocks [L, ...], rest).

    ``rest`` keeps embed / final norm / lm_head, which stay replicated.
    """
    blocks = [params[f"block_{i}"] for i in range(num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    return stacked, rest


def unstack_block_params(stacked, rest, num_layers: int):
    """Inverse of :func:`stack_block_params`."""
    out = dict(rest)
    for i in range(num_layers):
        out[f"block_{i}"] = jax.tree.map(lambda a: a[i], stacked)
    return out


def make_pp_lm_train_step(model, base_opt: optax.GradientTransformation,
                          mesh: Mesh, num_microbatches: int,
                          donate: bool = True):
    """Pipeline-parallel LM train step over ``mesh``'s ``pp`` axis.

    ``tokens``/``targets`` ``[B, T]`` with ``B %% num_microbatches == 0``;
    the stacked block parameters are sharded one layer-group per stage,
    embed/head replicate.  Returns ``step(stacked, rest, opt_state, tokens,
    targets) -> (stacked, rest, opt_state, loss)``; build inputs with
    :func:`stack_block_params`.
    """
    from ..models.transformer import Block  # deferred: avoids import cycle
    from ..ops.ring_attention import attention as _attn

    cfg = model.config
    S = mesh.devices.size
    L = cfg.num_layers
    M = num_microbatches
    if L % S:
        raise ValueError(f"num_layers {L} must divide into {S} stages")
    K = L // S
    block = Block(cfg.num_heads, cfg.dtype, cfg.mlp_ratio,
                  cfg.num_experts, cfg.capacity_factor)

    def apply_stage(stage_params, h, positions):
        """Apply this stage's K blocks ([K, ...] leaves) sequentially."""
        def body(carry, p):
            out = block.apply(
                {"params": p}, carry,
                lambda q, k, v: _attn(q, k, v, causal=True), positions)
            return out, None
        h, _ = lax.scan(body, h, stage_params)
        return h

    def pipe_forward(stacked, rest, tokens):
        """shard_map body: tokens [B, T] replicated; stacked has [K,...]
        leaves (this stage's slice); returns logits [B, T, V]."""
        stage = lax.axis_index("pp")
        B, T = tokens.shape
        Bm = B // M
        positions = jnp.arange(T)
        micro = _embed(rest, tokens.reshape(M, Bm, T), cfg)  # [M, Bm, T, D]

        D = micro.shape[-1]
        perm = [(j, (j + 1) % S) for j in range(S)]
        _vary = lambda a: lax.pcast(a, "pp", to="varying")
        out_buf = _vary(jnp.zeros((M, Bm, T, D), micro.dtype))
        state = _vary(jnp.zeros((Bm, T, D), micro.dtype))

        def tick(carry, t):
            state, out_buf = carry
            # stage 0 injects microbatch t (or zeros in the drain phase)
            feed = micro[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where(stage == 0,
                             jnp.where(t < M, feed, jnp.zeros_like(feed)),
                             state)
            h_out = apply_stage(stacked, h_in, positions)
            # last stage banks microbatch t-(S-1) once it emerges
            emit_idx = t - (S - 1)
            valid = (stage == S - 1) & (emit_idx >= 0)
            slot = jnp.clip(emit_idx, 0, M - 1)
            banked = jnp.where(valid, h_out, out_buf[slot])
            out_buf = lax.dynamic_update_index_in_dim(out_buf, banked,
                                                      slot, 0)
            state = lax.ppermute(h_out, "pp", perm)
            return (state, out_buf), None

        (_, out_buf), _ = lax.scan(tick, (state, out_buf),
                                   jnp.arange(M + S - 1))
        # only the last stage holds real outputs; broadcast them to all
        # stages so the (replicated) head + loss see the true activations
        masked = jnp.where(stage == S - 1, out_buf,
                           jnp.zeros_like(out_buf))
        out = lax.psum(masked, "pp")
        return _head(rest, out.reshape(B, T, D), cfg)

    def global_loss(stacked, rest, tokens, targets):
        def shard_fn(stk, rst, tok, tgt):
            stk = jax.tree.map(lambda a: a[0], stk)   # [1,K,...] -> [K,...]
            logits = pipe_forward(stk, rst, tok)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()
            return lax.pmean(loss, "pp")

        # stacked leaves are [S*K, ...]; shard the leading axis over pp
        stacked4 = jax.tree.map(
            lambda a: a.reshape((S, K) + a.shape[1:]), stacked)
        return jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=P())(stacked4, rest, tokens, targets)

    def stepper(stacked, rest, opt_state, tokens, targets):
        if tokens.shape[0] % M:
            raise ValueError(
                f"batch {tokens.shape[0]} must be divisible by "
                f"num_microbatches {M}")
        loss, grads = jax.value_and_grad(global_loss, argnums=(0, 1))(
            stacked, rest, tokens, targets)
        params = (stacked, rest)
        updates, opt_state = base_opt.update(grads, opt_state, params)
        stacked, rest = optax.apply_updates(params, updates)
        return stacked, rest, opt_state, loss

    return jax.jit(stepper, donate_argnums=(0, 1, 2) if donate else ())


import flax.linen as nn  # noqa: E402  (module helpers below)


def _embed(rest, tokens, cfg):
    """Embedding lookup from the replicated non-block params (every stage
    computes it; only stage 0's result feeds the pipeline)."""
    return nn.Embed(cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype).apply(
        {"params": rest["embed"]}, tokens)


def _head(rest, x, cfg):
    """Final norm + LM head from the replicated non-block params."""
    x = nn.LayerNorm(dtype=cfg.dtype).apply({"params": rest["ln_f"]}, x)
    return nn.Dense(cfg.vocab_size, dtype=jnp.float32).apply(
        {"params": rest["lm_head"]}, x)
