"""Pipeline parallelism: synchronous 1F1B microbatch schedule over a mesh axis.

No reference counterpart (SURVEY.md §2.6: PP absent in BlueFog); built
because layer pipelining is the remaining first-class TPU scaling axis.

Design: every stage runs the *same* jitted program (shard_map over a ``pp``
axis); stage ``s`` owns layers ``[s*K, (s+1)*K)`` as a stacked parameter
tree sharded on its leading axis.  The schedule is the classic synchronous
**1F1B** profile expressed as one ``lax.scan`` over ``M + 2S - 2`` ticks:

* tick ``t``, forward slot: stage ``s`` runs microbatch ``t - s`` (if in
  range), stashing only the stage *input*;
* tick ``t``, backward slot: stage ``s`` back-propagates microbatch
  ``t - (2S - 2 - s)``, recomputing its forward from the stashed input
  (``jax.vjp``) — activation-recompute 1F1B, so the in-flight stash is
  bounded by ``min(M, 2S-1)`` microbatch activations per stage instead of
  GPipe's ``M``;
* activations ``ppermute`` rightward and cotangents leftward once per tick
  (nearest-neighbor ICI), and gradients accumulate locally.

Stage-divergent work is a runtime branch (``lax.cond`` on
``lax.axis_index``): the embedding runs **only on stage 0**, the LM head /
loss / their gradients **only on the last stage**, and bubble ticks skip
the block compute entirely — none of the GPipe-era redundancy (every stage
embedding all microbatches and running the head over the full batch).

Backward is constructed manually (per-tick ``jax.vjp``), not by
differentiating the scan, which is what lets forward and backward
interleave in one loop — ``jax.grad`` of a forward-only pipeline would
serialize all forwards before any backward and stash all ``M`` microbatch
inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["stack_block_params", "unstack_block_params",
           "make_pp_lm_train_step", "pp_mesh",
           "fwd_microbatch", "bwd_microbatch", "num_ticks", "stash_bound"]


# ---------------------------------------------------------------------------
# The 1F1B schedule (pure functions — unit-testable)
# ---------------------------------------------------------------------------

def num_ticks(num_microbatches: int, stages: int) -> int:
    """Total scan ticks: M + 2(S-1)."""
    return num_microbatches + 2 * (stages - 1)


def fwd_microbatch(stage: int, tick: int) -> int:
    """Microbatch index stage ``stage`` forwards at ``tick`` (may be out of
    [0, M) — then the stage's forward slot idles)."""
    return tick - stage


def bwd_microbatch(stage: int, tick: int, stages: int) -> int:
    """Microbatch index stage ``stage`` back-propagates at ``tick``."""
    return tick - (2 * stages - 2 - stage)


def stash_bound(num_microbatches: int, stages: int) -> int:
    """Max in-flight stage-input stashes per stage: min(M, 2S-1) —
    the 1F1B memory bound (GPipe stores M)."""
    return min(num_microbatches, 2 * stages - 1)


# ---------------------------------------------------------------------------
# Parameter layout helpers
# ---------------------------------------------------------------------------

def pp_mesh(stages: int, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[:stages])
    if devices.size != stages:
        raise ValueError(f"need {stages} devices, have {devices.size}")
    return Mesh(devices.reshape(stages), ("pp",))


def stack_block_params(params, num_layers: int):
    """Split a Transformer params tree into (stacked blocks [L, ...], rest).

    ``rest`` keeps embed / final norm / lm_head; embed lives on stage 0 and
    the head on the last stage at runtime, but the tree stays replicated so
    every stage's program is identical.
    """
    blocks = [params[f"block_{i}"] for i in range(num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    return stacked, rest


def unstack_block_params(stacked, rest, num_layers: int):
    """Inverse of :func:`stack_block_params`."""
    out = dict(rest)
    for i in range(num_layers):
        out[f"block_{i}"] = jax.tree.map(lambda a: a[i], stacked)
    return out


# ---------------------------------------------------------------------------
# Train step
# ---------------------------------------------------------------------------

def make_pp_lm_train_step(model, base_opt: optax.GradientTransformation,
                          mesh: Mesh, num_microbatches: int,
                          donate: bool = True):
    """1F1B pipeline-parallel LM train step over ``mesh``'s ``pp`` axis.

    ``tokens``/``targets`` ``[B, T]`` with ``B %% num_microbatches == 0``;
    the stacked block parameters are sharded one layer-group per stage,
    embed/head replicate (computed only on their owning stage).  Returns
    ``step(stacked, rest, opt_state, tokens, targets) -> (stacked, rest,
    opt_state, loss)``; build inputs with :func:`stack_block_params`.
    """
    from ..models.transformer import Block  # deferred: avoids import cycle
    from ..ops.flash_attention import best_attention

    cfg = model.config
    S = mesh.devices.size
    L = cfg.num_layers
    M = num_microbatches
    if L % S:
        raise ValueError(f"num_layers {L} must divide into {S} stages")
    K = L // S
    TT = num_ticks(M, S)
    C = stash_bound(M, S)
    block = Block(cfg.num_heads, cfg.dtype, cfg.mlp_ratio,
                  cfg.num_experts, cfg.capacity_factor)
    attn = lambda q, k, v: best_attention(q, k, v, causal=True)

    def apply_blocks(stage_params, h, positions):
        """This stage's K blocks ([K, ...] leaves), sequentially."""
        def body(carry, p):
            return block.apply({"params": p}, carry, attn, positions), None
        h, _ = lax.scan(body, h, stage_params)
        return h

    def embed_fn(rest, tok):
        return _embed(rest, tok, cfg)

    def head_loss(rest, h, tgt):
        logits = _head(rest, h, cfg)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    def pipe_step(stacked, rest, tokens, targets):
        """shard_map body.  ``stacked``: this stage's [K, ...] leaves;
        ``rest``/``tokens``/``targets`` replicated.  Returns (g_stacked,
        g_rest_partial, loss_partial) — caller psums the partials."""
        stage = lax.axis_index("pp")
        B, T = tokens.shape
        Bm = B // M
        positions = jnp.arange(T)
        tok_mb = tokens.reshape(M, Bm, T)
        tgt_mb = targets.reshape(M, Bm, T)
        D = cfg.embed_dim
        hshape = (Bm, T, D)
        hdtype = cfg.dtype
        perm_r = [(j, (j + 1) % S) for j in range(S)]
        perm_l = [(j, (j - 1) % S) for j in range(S)]

        def _vary(a):
            # idempotent pcast: leaves already varying over pp pass through
            return jax.tree.map(
                lambda x: x if "pp" in getattr(jax.typeof(x), "vma", ())
                else lax.pcast(x, "pp", to="varying"), a)
        # Mark the replicated params varying BEFORE any vjp touches them:
        # the transpose of an invariant->varying broadcast is a psum, and a
        # psum inside a stage-gated lax.cond would be a collective only some
        # devices execute (deadlock).  Varying in, varying cotangent out —
        # the single explicit psum below happens on every device.
        rest = _vary(rest)
        # cond/scan branches must agree on varying-mesh-axis types, so every
        # "zero" alternative is explicitly marked varying over pp
        zeros_h = lambda: _vary(jnp.zeros(hshape, hdtype))
        zeros_rest = lambda: _vary(jax.tree.map(jnp.zeros_like, rest))
        zeros_scal = lambda: _vary(jnp.zeros((), jnp.float32))
        g_stacked0 = jax.tree.map(jnp.zeros_like, stacked)
        g_rest0 = jax.tree.map(jnp.zeros_like, rest)

        carry0 = (
            zeros_h(),                             # h_send (rightward)
            zeros_h(),                             # g_send (leftward)
            _vary(jnp.zeros((C,) + hshape, hdtype)),   # stash of stage inputs
            g_stacked0,             # already varying (zeros of the shard)
            _vary(g_rest0),
            zeros_scal(),                          # loss sum (last stage)
        )

        def tick(carry, t):
            h_recv, g_recv, stash, g_blocks, g_rest, loss_sum = carry

            # ---- forward slot: microbatch t - stage -----------------------
            m_f = t - stage
            valid_f = (m_f >= 0) & (m_f < M)
            mf_c = jnp.clip(m_f, 0, M - 1)

            def fwd_compute():
                h_in = lax.cond(
                    stage == 0,
                    lambda: _vary(embed_fn(rest, tok_mb[mf_c])
                                  .astype(hdtype)),
                    lambda: h_recv)
                return h_in, apply_blocks(stacked, h_in, positions)

            # bubble ticks skip block AND embed compute entirely
            h_in, h_out = lax.cond(valid_f, fwd_compute,
                                   lambda: (zeros_h(), zeros_h()))
            slot_f = jnp.where(valid_f, m_f % C, 0)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(valid_f, h_in, stash[slot_f]), slot_f, 0)

            # ---- backward slot: microbatch t - (2S-2-stage) ---------------
            m_b = t - (2 * S - 2 - stage)
            valid_b = (m_b >= 0) & (m_b < M)
            mb_c = jnp.clip(m_b, 0, M - 1)
            slot_b = jnp.where(valid_b, m_b % C, 0)

            def run_bwd():
                h_in_b = stash[slot_b]
                h_out_b, f_vjp = jax.vjp(
                    lambda p, h: apply_blocks(p, h, positions),
                    stacked, h_in_b)

                def g_from_loss():
                    # last stage: head + loss gradients for this microbatch
                    loss_m, (g_h, g_r) = jax.value_and_grad(
                        lambda h_, r_: head_loss(r_, h_, tgt_mb[mb_c]),
                        argnums=(0, 1))(h_out_b, rest)
                    return (_vary(loss_m), _vary(g_h.astype(hdtype)),
                            _vary(g_r))

                def g_from_right():
                    return zeros_scal(), g_recv, zeros_rest()

                loss_m, g_out, g_rest_head = lax.cond(
                    stage == S - 1, g_from_loss, g_from_right)
                gb, g_h_in = f_vjp(g_out)

                def g_embed():
                    # stage 0: continue the chain through the embedding
                    _, evjp = jax.vjp(lambda r: embed_fn(r, tok_mb[mb_c])
                                      .astype(hdtype), rest)
                    return _vary(evjp(g_h_in)[0])

                g_rest_emb = lax.cond(stage == 0, g_embed, zeros_rest)
                g_rest_m = jax.tree.map(lambda a, b: a + b,
                                        g_rest_head, g_rest_emb)
                return gb, g_rest_m, g_h_in, loss_m

            def skip_bwd():
                return (jax.tree.map(jnp.zeros_like, stacked),
                        zeros_rest(), zeros_h(), zeros_scal())

            gb, g_rest_m, g_h_in, loss_m = lax.cond(valid_b, run_bwd,
                                                    skip_bwd)
            g_blocks = jax.tree.map(lambda a, b: a + b, g_blocks, gb)
            g_rest = jax.tree.map(lambda a, b: a + b, g_rest, g_rest_m)
            loss_sum = loss_sum + loss_m

            # ---- exchanges: activations right, cotangents left ------------
            h_send = lax.ppermute(h_out, "pp", perm_r)
            g_send = lax.ppermute(g_h_in, "pp", perm_l)
            return (h_send, g_send, stash, g_blocks, g_rest, loss_sum), None

        (_, _, _, g_blocks, g_rest, loss_sum), _ = lax.scan(
            tick, carry0, jnp.arange(TT))

        # scale: losses are per-microbatch means; grads accumulated over M
        inv_m = 1.0 / M
        g_blocks = jax.tree.map(lambda a: a * inv_m, g_blocks)
        g_rest = jax.tree.map(lambda a: lax.psum(a * inv_m, "pp"), g_rest)
        loss = lax.psum(loss_sum * inv_m, "pp")
        return g_blocks, g_rest, loss

    def compute_grads(stacked, rest, tokens, targets):
        stacked4 = jax.tree.map(
            lambda a: a.reshape((S, K) + a.shape[1:]), stacked)

        def shard_fn(stk, rst, tok, tgt):
            stk = jax.tree.map(lambda a: a[0], stk)   # [1, K, ...] -> [K, ...]
            gb, gr, loss = pipe_step(stk, rst, tok, tgt)
            return jax.tree.map(lambda a: a[None], gb), gr, loss

        g4, g_rest, loss = jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=(P("pp"), P(), P()))(stacked4, rest, tokens, targets)
        g_stacked = jax.tree.map(
            lambda a: a.reshape((S * K,) + a.shape[2:]), g4)
        return g_stacked, g_rest, loss

    def stepper(stacked, rest, opt_state, tokens, targets):
        if tokens.shape[0] % M:
            raise ValueError(
                f"batch {tokens.shape[0]} must be divisible by "
                f"num_microbatches {M}")
        g_stacked, g_rest, loss = compute_grads(stacked, rest, tokens,
                                                targets)
        params = (stacked, rest)
        updates, opt_state = base_opt.update((g_stacked, g_rest), opt_state,
                                             params)
        stacked, rest = optax.apply_updates(params, updates)
        return stacked, rest, opt_state, loss

    return jax.jit(stepper, donate_argnums=(0, 1, 2) if donate else ())


import flax.linen as nn  # noqa: E402  (module helpers below)


def _embed(rest, tokens, cfg):
    """Embedding lookup from the replicated non-block params (runs only on
    stage 0 at runtime via lax.cond)."""
    return nn.Embed(cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype).apply(
        {"params": rest["embed"]}, tokens)


def _head(rest, x, cfg):
    """Final norm + LM head from the replicated non-block params (runs only
    on the last stage at runtime via lax.cond)."""
    x = nn.LayerNorm(dtype=cfg.dtype).apply({"params": rest["ln_f"]}, x)
    return nn.Dense(cfg.vocab_size, dtype=jnp.float32).apply(
        {"params": rest["lm_head"]}, x)
