"""Dynamic (per-step) one-peer topology schedules.

The reference exposes per-rank generators that yield ``(send_ranks,
recv_ranks)`` each iteration (``bluefog/common/topology_util.py:315-554``).
Those generators are deterministic in ``(rank, size, step)``, which is what
makes them usable from a single-controller SPMD program: we evaluate the rule
for *every* rank at once and materialize the step's global mixing matrix (or
its ppermute offset), instead of each MPI process privately asking "who do I
talk to now".

Per-rank generator API is kept for parity/tests; the matrix/offset helpers at
the bottom are what the TPU collectives consume.

Reference parity map:
  * GetDynamicOnePeerSendRecvRanks          topology_util.py:315
  * GetExp2DynamicSendRecvMachineRanks      topology_util.py:360
  * GetInnerOuterRingDynamicSendRecvRanks   topology_util.py:399
  * GetInnerOuterExpo2DynamicSendRecvRanks  topology_util.py:466
"""

import math
from typing import Callable, Iterator, List, Tuple

import numpy as np
import networkx as nx

__all__ = [
    "GetDynamicOnePeerSendRecvRanks",
    "GetExp2DynamicSendRecvMachineRanks",
    "GetInnerOuterRingDynamicSendRecvRanks",
    "GetInnerOuterExpo2DynamicSendRecvRanks",
    "one_peer_send_rank",
    "one_peer_factory",
    "dynamic_mixing_matrix",
    "dynamic_mixing_matrices",
    "dynamic_mixing_matrices_with_liveness",
    "one_peer_offsets",
]


# ---------------------------------------------------------------------------
# Closed-form per-step rules (shared by the generators and the global helpers)
# ---------------------------------------------------------------------------

def _sorted_out_neighbors(topo: nx.DiGraph) -> List[List[int]]:
    """Out-neighbors of each rank sorted clockwise (by positive offset)."""
    size = topo.number_of_nodes()
    result = []
    for rank in range(size):
        succ = [r for r in topo.successors(rank) if r != rank]
        succ.sort(key=lambda r: (r - rank) % size)
        result.append(succ)
    return result


def one_peer_send_rank(topo: nx.DiGraph, rank: int, step: int) -> int:
    """Destination of ``rank`` at ``step`` under the one-peer rotation rule.

    Rank r cycles clockwise through its non-self out-neighbors; the cycle
    length is r's own out-degree, so ranks with different degrees rotate at
    different periods (matching reference topology_util.py:344-357).
    """
    ordered = _sorted_out_neighbors(topo)[rank]
    return ordered[step % len(ordered)]


def one_peer_factory(topo: nx.DiGraph) -> "GeneratorFactory":
    """The per-rank generator family for the one-peer rotation over
    ``topo`` — the ``factory`` shape :func:`dynamic_mixing_matrices`,
    ``compile_dynamic_schedule``, and the controller's
    ``control.build_switchable_schedule`` consume."""
    return lambda rank: GetDynamicOnePeerSendRecvRanks(topo, rank)


def GetDynamicOnePeerSendRecvRanks(
        topo: nx.DiGraph, self_rank: int) -> Iterator[Tuple[List[int], List[int]]]:
    """Yield ([send_rank], recv_ranks) per step: one outgoing peer, cycling
    clockwise through the base topology's out-neighbors."""
    size = topo.number_of_nodes()
    ordered = _sorted_out_neighbors(topo)
    step = 0
    while True:
        send = ordered[self_rank][step % len(ordered[self_rank])]
        recv = [
            r for r in range(size)
            if r != self_rank and ordered[r][step % len(ordered[r])] == self_rank
        ]
        yield [send], recv
        step += 1


def _exp2_machine_dist(machine_size: int, step: int) -> int:
    n_shifts = int(np.log2(machine_size - 1)) + 1 if machine_size > 1 else 1
    return 2 ** (step % n_shifts)


def GetExp2DynamicSendRecvMachineRanks(
        world_size: int, local_size: int, self_rank: int, local_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Machine-level exponential-2 one-peer schedule (homogeneous clusters)."""
    if self_rank % local_size != local_rank or world_size % local_size != 0:
        raise ValueError("requires a homogeneous environment")
    if world_size <= local_size:
        raise ValueError("requires at least two machines")
    machine_id = self_rank // local_size
    machine_size = world_size // local_size
    step = 0
    while True:
        dist = _exp2_machine_dist(machine_size, step)
        yield [(machine_id + dist) % machine_size], [(machine_id - dist) % machine_size]
        step += 1


def _inner_outer_pair(world_size: int, local_size: int, rank: int, step: int,
                      inner_dist_fn: Callable[[int, int], int],
                      outer_dist_fn: Callable[[int], int]) -> Tuple[int, int]:
    """Shared structure of the inner/outer schedules.

    Per step, exactly one local rank per machine (``step % local_size``) talks
    to another machine at ``outer_dist_fn(step)`` machines away (same local
    slot); everyone else moves data around the machine-internal graph, with
    ``inner_dist_fn(step, dist_to_outgoing)`` skipping over the outgoing rank.
    Returns (send_rank, recv_rank).
    """
    num_machines = world_size // local_size
    machine_id, local_id = divmod(rank, local_size)
    outgoing_local = step % local_size

    if local_id == outgoing_local:
        dist = outer_dist_fn(step)
        send = ((machine_id + dist) % num_machines) * local_size + local_id
        recv = ((machine_id - dist) % num_machines) * local_size + local_id
        return send, recv

    fwd = inner_dist_fn(step, (outgoing_local - local_id) % local_size)
    send = machine_id * local_size + (local_id + fwd) % local_size
    bwd = inner_dist_fn(step, (local_id - outgoing_local) % local_size)
    recv = machine_id * local_size + (local_id - bwd) % local_size
    return send, recv


def GetInnerOuterRingDynamicSendRecvRanks(
        world_size: int, local_size: int, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Inner-ring/outer-ring one-peer schedule."""
    if world_size % local_size != 0:
        raise ValueError("requires a homogeneous environment")
    if local_size <= 2:
        raise ValueError(
            "needs more than 2 ranks per machine; use "
            "hierarchical_neighbor_allreduce or GetDynamicOnePeerSendRecvRanks"
        )

    def inner(step, dist_to_out):
        # next rank on the local ring, skipping the outgoing one
        return 2 if dist_to_out == 1 else 1

    step = 0
    while True:
        send, recv = _inner_outer_pair(
            world_size, local_size, self_rank, step, inner, lambda s: 1)
        yield [send], [recv]
        step += 1


def GetInnerOuterExpo2DynamicSendRecvRanks(
        world_size: int, local_size: int, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Inner-exp2/outer-exp2 one-peer schedule (the flagship dynamic graph)."""
    if world_size % local_size != 0:
        raise ValueError("requires a homogeneous environment")
    if local_size <= 2:
        raise ValueError(
            "needs more than 2 ranks per machine; use "
            "hierarchical_neighbor_allreduce or GetDynamicOnePeerSendRecvRanks"
        )
    num_machines = world_size // local_size
    n_outer = int(np.log2(num_machines - 1)) + 1
    n_inner = (int(np.log2(local_size - 2)) if local_size > 2 else 0) + 1

    def inner(step, dist_to_out):
        d = 2 ** (step % n_inner)
        return d + 1 if d >= dist_to_out else d

    def outer(step):
        return 2 ** (step % n_outer)

    step = 0
    while True:
        send, recv = _inner_outer_pair(
            world_size, local_size, self_rank, step, inner, outer)
        yield [send], [recv]
        step += 1


# ---------------------------------------------------------------------------
# Global (SPMD) views: full per-step mixing matrices / ppermute offsets
# ---------------------------------------------------------------------------

GeneratorFactory = Callable[[int], Iterator[Tuple[List[int], List[int]]]]


def dynamic_mixing_matrix(size: int, send_ranks_per_rank: List[List[int]]) -> np.ndarray:
    """Mixing matrix for one dynamic step.

    ``send_ranks_per_rank[i]`` lists where rank i pushes this step.  Receive
    weights follow the reference convention ``1 / (num_sources + 1)`` shared
    with the self loop (examples/pytorch_resnet.py dynamic_topology_update).
    ``W[i, j]`` = weight of rank i's value in rank j's average.
    """
    W = np.zeros((size, size))
    for src, dsts in enumerate(send_ranks_per_rank):
        for dst in dsts:
            W[src, dst] = 1.0
    in_count = W.sum(axis=0)  # sources per destination, excl. self
    W /= (in_count + 1.0)[None, :]
    np.fill_diagonal(W, 1.0 / (in_count + 1.0))
    return W


def dynamic_mixing_matrices(factory: GeneratorFactory, size: int,
                            num_steps: int) -> np.ndarray:
    """Stack of ``[num_steps, size, size]`` mixing matrices for a schedule.

    ``factory(rank)`` must return the per-rank generator (e.g.
    ``lambda r: GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)``).
    """
    gens = [factory(r) for r in range(size)]
    mats = []
    for _ in range(num_steps):
        sends = [next(g)[0] for g in gens]
        mats.append(dynamic_mixing_matrix(size, sends))
    return np.stack(mats)


def dynamic_mixing_matrices_with_liveness(factory: GeneratorFactory,
                                          size: int, num_steps: int,
                                          alive) -> np.ndarray:
    """Liveness-masked variant of :func:`dynamic_mixing_matrices`: the
    one-peer rule still rotates over the FULL rank set (so the schedule's
    period and offset superset never change and compiled programs stay
    valid), but steps touching dead ranks are repaired — the dead edge's
    weight moves to the survivor's self loop (column-stochasticity
    preserved; see ``resilience.repair.liveness_masked_matrices``)."""
    from ..resilience.repair import liveness_masked_matrices
    return liveness_masked_matrices(
        dynamic_mixing_matrices(factory, size, num_steps), alive)


def one_peer_offsets(factory: GeneratorFactory, size: int,
                     num_steps: int) -> np.ndarray:
    """Per-step ppermute shift for schedules where every rank sends to the
    same relative offset (exp2 one-peer on a circulant base graph).

    Returns ``offsets[num_steps]`` with ``send_rank = (rank + offset) % size``;
    raises if any step is not a uniform rotation (then use
    ``dynamic_mixing_matrices`` instead).
    """
    gens = [factory(r) for r in range(size)]
    offsets = []
    for step in range(num_steps):
        sends = [next(g)[0] for g in gens]
        offs = {(s[0] - r) % size for r, s in enumerate(sends)}
        if len(offs) != 1:
            raise ValueError(
                f"step {step}: schedule is not a uniform rotation {sends}")
        offsets.append(offs.pop())
    return np.asarray(offsets, dtype=np.int32)


def schedule_period(factory: GeneratorFactory, size: int,
                    max_period: int = 4096) -> int:
    """Smallest p such that the schedule's send pattern repeats with period p.

    Verified over a window of ``2 * max_period`` observed steps: candidate p
    must satisfy ``sends[i] == sends[i % p]`` for every step in the window,
    so a pattern like 1,2,1,3 cannot be mistaken for period 2 just because
    step 2 matches step 0.
    """
    gens = [factory(r) for r in range(size)]
    seq = []

    def extend(to_len):
        while len(seq) < to_len:
            seq.append(tuple(tuple(next(g)[0]) for g in gens))

    window = 64
    while True:
        extend(window)
        for p in range(1, window // 2 + 1):
            if all(seq[i] == seq[i % p] for i in range(window)):
                # confirm over a larger horizon before accepting
                extend(min(4 * p + 16, 2 * max_period))
                if all(seq[i] == seq[i % p] for i in range(len(seq))):
                    return p
        if window >= 2 * max_period:
            raise ValueError(f"no period found within {max_period} steps")
        window = min(2 * window, 2 * max_period)
