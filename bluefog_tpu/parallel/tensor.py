"""Tensor parallelism: Megatron-style sharding rules via GSPMD.

No reference counterpart (SURVEY.md §2.6: TP absent in BlueFog — "no weight
sharding anywhere"); built because weight sharding is a core TPU scaling
axis.  The idiomatic TPU implementation is *declarative*: place parameter
leaves with ``NamedSharding`` over a ``(dp, tp)`` mesh and let XLA's SPMD
partitioner insert the all-gathers/reduce-scatters — no hand-written
collectives (the How-to-Scale-Your-Model recipe: pick a mesh, annotate
shardings, let XLA do the rest).

Rules follow the Megatron pattern for the Transformer family
(``models/transformer.py``):

  * qkv projection: split the heads dimension (column parallel)
  * attention output projection: split the heads dimension (row parallel)
  * MLP up: split the hidden dimension (column), MLP down: row
  * MoE experts: split the expert dimension
  * embeddings / norms / router: replicated over tp

Gradients and optimizer states inherit the parameter shardings through
jit's sharding propagation, so the Adam mirror of a sharded weight is
sharded identically for free.
"""

import collections
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["transformer_tp_rules", "shard_params", "make_tp_lm_train_step",
           "make_decentralized_tp_lm_train_step",
           "make_decentralized_sharded_lm_train_step", "tp_mesh",
           "sharded_neighbor_mix", "sharded_delayed_mix",
           "hybrid_inflight_state"]

# bflint knob-outside-cache-key: these builders return a fresh jitted
# step per call (no shared step cache); ``topo`` is keyed by context
# identity where a cache exists, ``sched`` is traced data, ``donate`` is
# build-structural.
_STEP_KEY_EXEMPT_KNOBS = frozenset({"topo", "sched", "donate"})

# (path regex, PartitionSpec factory given tp axis name); first match wins
_TP_RULES = [
    (r"qkv/kernel$",      lambda tp: P(None, None, tp, None)),  # [D,3,H,hd]
    (r"qkv/bias$",        lambda tp: P(None, tp, None)),        # [3,H,hd]
    (r"proj/kernel$",     lambda tp: P(tp, None, None)),        # [H,hd,D]
    (r"mlp_up/kernel$",   lambda tp: P(None, tp)),              # [D,Hm]
    (r"mlp_up/bias$",     lambda tp: P(tp)),                    # [Hm]
    (r"mlp_down/kernel$", lambda tp: P(tp, None)),              # [Hm,D]
    (r"moe/w_up$",        lambda tp: P(tp, None, None)),        # [E,D,Hm]
    (r"moe/b_up$",        lambda tp: P(tp, None)),
    (r"moe/w_down$",      lambda tp: P(tp, None, None)),
    (r"moe/b_down$",      lambda tp: P(tp, None)),
    (r"lm_head/kernel$",  lambda tp: P(None, tp)),              # [D,V]
    (r"lm_head/bias$",    lambda tp: P(tp)),
]


def transformer_tp_rules(params, tp_axis: str = "tp"):
    """PartitionSpec pytree for a Transformer params tree (unmatched leaves
    replicate)."""
    def spec_for(path, leaf):
        name = jax.tree_util.keystr(path, simple=True, separator="/")
        for pat, mk in _TP_RULES:
            if re.search(pat, name):
                spec = mk(tp_axis)
                if len(spec) <= leaf.ndim:
                    return spec
        return P()
    return jax.tree_util.tree_map_with_path(spec_for, params)


def tp_mesh(dp: int, tp: int, devices=None) -> Mesh:
    """A ``(dp, tp)`` mesh; tp should map to the fastest (ICI-adjacent)
    axis, which is the trailing one in the device array."""
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[: dp * tp])
    if devices.size != dp * tp:
        raise ValueError(f"need {dp * tp} devices, have {devices.size}")
    return Mesh(devices.reshape(dp, tp), ("dp", "tp"))


def shard_params(params, mesh: Mesh, tp_axis: str = "tp"):
    """Place a replicated params tree according to the TP rules."""
    specs = transformer_tp_rules(params, tp_axis)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs)


def make_tp_lm_train_step(model, base_opt: optax.GradientTransformation,
                          mesh: Mesh, donate: bool = True):
    """Data+tensor-parallel LM train step on a ``(dp, tp)`` mesh.

    Tokens/targets ``[B, T]`` are batch-sharded over ``dp``; parameters are
    sharded by :func:`transformer_tp_rules` over ``tp``.  The step is a
    plain jitted ``value_and_grad`` — XLA's partitioner derives every
    collective (all-gather of column-parallel outputs, psum of row-parallel
    partials, gradient reduce-scatter) from the in/out shardings.

    Returns ``(step_fn, place_fn)``: ``place_fn(params, opt_state)`` puts a
    freshly initialized state onto the mesh; ``step_fn(params, opt_state,
    tokens, targets) -> (params, opt_state, loss)``.
    """
    data_sharding = NamedSharding(mesh, P("dp", None))

    def place(params, opt_state):
        params = shard_params(params, mesh)
        return params, _shard_like(opt_state, params, mesh)

    def _loss(p, tokens, targets):
        logits = model.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    @jax.jit
    def step(params, opt_state, tokens, targets):
        tokens = jax.lax.with_sharding_constraint(tokens, data_sharding)
        targets = jax.lax.with_sharding_constraint(targets, data_sharding)
        loss, grads = jax.value_and_grad(_loss)(params, tokens, targets)
        updates, opt_state = base_opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    if donate:
        step = jax.jit(step.__wrapped__, donate_argnums=(0, 1))
    return step, place


def make_decentralized_tp_lm_train_step(
        model, base_opt: optax.GradientTransformation, mesh: Mesh,
        topo=None, sched=None, donate: bool = True, **comm_kwargs):
    """Decentralized DP composed with TP on ONE ``(dp, tp)`` mesh.

    The framework's flagship composition (VERDICT r1 item 7): the ``dp``
    axis runs BlueFog-style *neighbor averaging of parameters* (static
    ``topo``, a :class:`~bluefog_tpu.parallel.schedule.CompiledTopology`, or
    dynamic ``sched`` selected by the traced step index) while ``tp``
    Megatron-shards every replica.  One jitted program: each replica's
    forward/backward/update is GSPMD-partitioned over ``tp`` (XLA inserts
    the all-gathers/psums from the sharding rules), and the decentralized
    exchange is a ``shard_map`` whose body ppermutes each ``(dp, tp)``
    cell's *parameter shard* over the ``dp`` axis — mixing is elementwise,
    so each tp cell exchanges only its own 1/tp of the weights (the
    composition is bandwidth-optimal, not an afterthought).

    Parameter leaves carry a leading replica axis: [dp, *param_shape],
    sharded ``P("dp", *tp_rule)``.  Returns ``(step_fn, place_fn)`` with
    ``step_fn(params, opt_state, tokens, targets, step) -> (params,
    opt_state, loss)``; ``tokens``/``targets`` are [dp, B_local, T].
    ``comm_kwargs`` (``fuse=``/``fusion_bucket_bytes=``/``overlap=``/
    ``compression=``/``telemetry=``) configure the unified comm hot path
    — see :func:`make_decentralized_sharded_lm_train_step`.
    """
    return make_decentralized_sharded_lm_train_step(
        model, base_opt, mesh, transformer_tp_rules,
        topo=topo, sched=sched, donate=donate, **comm_kwargs)


def _spec_leaves(specs):
    """Flatten a PartitionSpec tree to its spec leaves (belt-and-braces
    ``is_leaf``: under some JAX versions P flattens as a container)."""
    return jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]


def _norm_spec(spec: P) -> P:
    """Strip trailing ``None`` spec entries so initial placements match
    the shard_map-normalized steady-state output shardings (single home:
    ``ops.fusion.norm_spec`` — mismatch recompiles the step on call 2)."""
    from ..ops import fusion as F
    return F.norm_spec(spec)


def _gossip_inner_axes(mesh: Mesh, gossip_axis: str):
    """The model-sharding axes of the hybrid mesh: everything that is not
    the gossip axis (fsdp / tp)."""
    if gossip_axis not in mesh.axis_names:
        raise ValueError(
            f"gossip axis {gossip_axis!r} is not an axis of the mesh "
            f"{tuple(mesh.axis_names)}")
    return tuple(a for a in mesh.axis_names if a != gossip_axis)


def _consensus_leaf_weights(inner_specs, mesh: Mesh, inner):
    """Per-leaf telemetry weights for the hybrid snapshot: 1 for leaves
    the inner axes shard fully, 1/replication for leaves they could not
    (every cell holds those whole — without the weight the psum over fsdp
    would count them fsdp times in the full-replica aggregates)."""
    total = 1
    for a in inner:
        total *= mesh.shape[a]

    def wt(spec):
        used = 1
        for entry in spec:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                if ax in inner:
                    used *= mesh.shape[ax]
        return used / total

    return jax.tree.map(wt, inner_specs,
                        is_leaf=lambda x: isinstance(x, P))


def hybrid_inflight_state(params_single, inner_specs, mesh: Mesh, *,
                          gossip_axis: str = "dp", fuse=None,
                          fusion_bucket_bytes=None):
    """Warmup in-flight exchange state for the OVERLAPPED hybrid step, in
    the global view the ``(dp, fsdp)`` train step carries: zero neighbor
    buffers plus self weight 1 (the step-0 fold is a pure local step —
    the ``delayed_init`` warmup encoding).

    Fused layout: one ``[dp, fsdp, padded_shard]`` flat buffer per shard-
    plan bucket, placed ``P(dp, fsdp)`` so each cell owns exactly the
    slice its shard_map body folds; unfused, the buffers mirror the
    parameter leaves with their within-replica specs.  The resolved
    fusion knobs must match the step builder's (the carried-buffer layout
    is part of the state structure)."""
    from ..ops import fusion as F
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    inner = _gossip_inner_axes(mesh, gossip_axis)
    lead = ((mesh.shape[gossip_axis],)
            + tuple(mesh.shape[a] for a in inner))
    zeros = F.sharded_zero_buffers(params_single, inner_specs, mesh,
                                   gossip_axis=gossip_axis, fuse=fuse,
                                   max_bucket_bytes=bucket)
    if fuse:
        bufs = tuple(zeros)
    else:
        bufs = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params_single), zeros)
    self_w = jax.device_put(jnp.ones(lead, jnp.float32),
                            NamedSharding(mesh, P(gossip_axis, *inner)))
    return {"bufs": bufs, "self_w": self_w}


def _hybrid_plumbing(mesh, gossip_axis, inner_specs, comp_state, fuse):
    """Shared strip/rewrap/spec/grouping machinery of the two hybrid
    mixers.

    Params-like leaves carry ONE leading gossip-axis dim in the global
    view (the fsdp axis lives inside the leaf dims via GSPMD sharding);
    buffer-like leaves (fused flat buckets, self weights, snapshot
    scalars) carry one leading dim per mesh axis.  ``groups`` partitions
    the fusion buckets by sharded-vs-replicated so a replicated leaf's
    codec output is identical on every fsdp cell
    (``ops/fusion.py::shard_groups``)."""
    from ..ops import fusion as F
    inner = _gossip_inner_axes(mesh, gossip_axis)
    groups = F.shard_groups(inner_specs, inner)
    n_lead = 1 + len(inner)
    pspecs = jax.tree.map(lambda s: P(gossip_axis, *s), inner_specs,
                          is_leaf=lambda x: isinstance(x, P))
    buf_spec = P(gossip_axis, *inner)
    strip_p = lambda t: jax.tree.map(lambda a: a[0], t)
    wrap_p = lambda t: jax.tree.map(lambda a: a[None], t)
    strip_b = lambda t: jax.tree.map(lambda a: a[(0,) * n_lead], t)
    wrap_b = lambda t: jax.tree.map(lambda a: a[(None,) * n_lead], t)
    if comp_state is None:
        cs_spec, strip_cs, wrap_cs = None, None, None
    elif fuse:
        cs_spec = jax.tree.map(lambda _: buf_spec, comp_state)
        strip_cs, wrap_cs = strip_b, wrap_b
    else:
        pl = tuple(P(gossip_axis, *s) for s in _spec_leaves(inner_specs))
        cs_spec = {k: pl for k in comp_state}
        strip_cs, wrap_cs = strip_p, wrap_p
    return (inner, groups, pspecs, buf_spec, strip_p, wrap_p, strip_b,
            wrap_b, cs_spec, strip_cs, wrap_cs)


# Traced-program cache for the standalone hybrid mixers.  Each call used
# to wrap a FRESH ``body`` closure in ``jax.shard_map`` and dispatch it
# EAGERLY — and an eager shard_map call re-lowers and re-compiles the
# whole exchange program every time (measured ~2-4 s/call on an 8-cell
# host mesh; only ``jax.jit`` gets the compiled-program fast path).  Each
# entry is ``(raw, jitted)``: eager callers run the jitted wrapper
# (compiled once per aval signature, ~ms afterwards); callers already
# inside an outer trace (the train-step builders) get the RAW wrapper so
# the emitted jaxpr — and the all-knobs-off byte-identical-StableHLO
# guarantee — is exactly what an inline shard_map produces.  Keyed on
# everything static that shapes the program; the closure holds strong
# refs to mesh/topo/sched, so an ``id()`` in a live key is never
# recycled.
_PROGRAM_CACHE = collections.OrderedDict()
_PROGRAM_CACHE_MAX = 64


def _cached_program(key, build):
    entry = _PROGRAM_CACHE.get(key)
    if entry is None:
        raw = build()
        entry = (raw, jax.jit(raw))
        _PROGRAM_CACHE[key] = entry
        while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
            _PROGRAM_CACHE.popitem(last=False)
    else:
        _PROGRAM_CACHE.move_to_end(key)
    return entry


def _pick_program(entry, operands):
    """Jitted wrapper for eager calls, raw shard_map under a trace."""
    raw, jitted = entry
    if any(isinstance(l, jax.core.Tracer)
           for l in jax.tree_util.tree_leaves(operands)):
        return raw
    return jitted


def _kernel_smap_kwargs(gk):
    """shard_map kwargs for a hybrid body that may run the gossip
    kernel: the pallas_call's scratch/semaphore machinery carries no
    varying-mesh-axes types, so vma checking must be off under the real
    kernel transports — the same rule the replicated steppers apply
    (``training.py``'s check_vma decision).  Off-path (``gk`` None or
    emulate) passes NOTHING, keeping the historical call byte-frozen
    (the 0.4.x compat shim drops the kwarg either way)."""
    return {"check_vma": False} if gk in ("pallas", "interpret") else {}


def _specs_key(inner_specs):
    leaves, treedef = jax.tree_util.tree_flatten(
        inner_specs, is_leaf=lambda x: isinstance(x, P))
    return treedef, tuple(leaves)


def sharded_neighbor_mix(params, step, *, mesh: Mesh, inner_specs,
                         gossip_axis: str = "dp", topo=None, sched=None,
                         fuse=None, fusion_bucket_bytes=None,
                         compression=None, comp_state=None,
                         telemetry: bool = False, grads=None,
                         old_params=None, gossip_kernel=None,
                         interleave=None):
    """One mesh-axis-aware decentralized exchange of a global-view
    ``[dp, ...]`` tree on a 2-level ``(dp, fsdp)``/``(dp, tp)`` mesh —
    the hybrid comm hot path.

    Inside one ``shard_map`` over the WHOLE mesh, each cell strips its
    local shard, runs the unified exchange
    (:func:`~bluefog_tpu.optim.strategies._communicate`: fusion buckets
    built over the SHARD shapes, compression codec encoding the 1/fsdp
    slice, every weight indexed by ``lax.axis_index(gossip_axis)``), and
    rewraps — so per-rank gossip traffic is 1/fsdp of the replicated
    path before compression even starts.

    Returns ``(mixed, new_comp_state, snapshot)``; the trailing two are
    ``None`` unless stateful compression / ``telemetry`` are active.
    ``telemetry=True`` needs ``grads=``/``old_params=`` and reports
    consensus over the GOSSIP axis only, with squared aggregates psummed
    over the model-sharding axes (full-replica health per rank).

    ``gossip_kernel``/``interleave`` (resolved through
    ``CX.effective_gossip_kernel`` like the replicated builders): run
    each cell's compressed bucket exchange as ONE fused kernel per
    bucket — the SAME ``strategies._communicate`` bucket-kernel entry
    the replicated path uses, with the kernel's RDMAs addressing the
    neighbor replica's matching cell via mesh-coordinate device ids
    (``kernel_mesh_axes``).  ``interleave=None`` takes the knob's
    resolved companion value.

    With every knob off this lowers byte-identical to the pre-hybrid
    per-leaf path (asserted in ``tests/test_hybrid.py``).

    The traced program is cached on the static config (mesh identity,
    gossip axis, spec tree, topo/sched identity, knobs) — repeat eager
    calls in a training loop re-trace nothing."""
    from ..compress import compressors as CP
    from ..compress import exchange as CX
    from ..observability import ingraph as IG
    from ..optim import strategies as S
    from ..ops import fusion as F

    if (topo is None) == (sched is None):
        raise ValueError("pass exactly one of topo= or sched=")
    cfg = CP.resolve_compression(compression)
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    CX.check_supported(cfg, comm_value="neighbor.allreduce", sched=sched,
                       overlap=False)
    gk, auto_il = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value="neighbor.allreduce", fuse=fuse)
    il = auto_il if interleave is None else bool(interleave)
    kmesh = tuple(mesh.axis_names) if gk is not None else None
    if CX.stateful(cfg) and comp_state is None:
        raise ValueError(
            "stateful compression needs comp_state= (create it with "
            "compress.exchange.sharded_state_layout)")
    comm = S.CommunicationType.neighbor_allreduce
    (inner, groups, pspecs, buf_spec, strip_p, wrap_p, _strip_b, wrap_b,
     cs_spec, strip_cs, wrap_cs) = _hybrid_plumbing(
        mesh, gossip_axis, inner_specs, comp_state, fuse)
    step = jnp.asarray(step, jnp.int32)

    if cfg is None and not telemetry and not fuse:
        # all-knobs-off: strip/mix/rewrap PER LEAF in one tree walk — the
        # exact emission order of the pre-hybrid per-leaf path, so the
        # disabled hybrid lowers to byte-identical StableHLO
        def body(p_shard, step_s):
            def mix_leaf(a):
                return S._communicate(
                    a[0], comm, gossip_axis, topo, sched, step_s,
                    None, None, "xla", False, bucket)[None]
            return jax.tree.map(mix_leaf, p_shard)
        entry = _cached_program(
            ("mix_legacy", id(mesh), gossip_axis, _specs_key(inner_specs),
             id(topo), id(sched), bucket),
            lambda: jax.shard_map(body, mesh=mesh, in_specs=(pspecs, P()),
                                  out_specs=pspecs))
        prog = _pick_program(entry, (params, step))
        return prog(params, step), None, None

    if telemetry and (grads is None or old_params is None):
        raise ValueError("telemetry=True needs grads= and old_params=")

    # the cached body must not close over comp_state itself: the closure
    # outlives the call and would pin the first call's (model-sized)
    # residual buffers for the cache entry's lifetime
    has_cs = comp_state is not None
    operands = [params, step]
    in_specs = [pspecs, P()]
    out_specs = [pspecs]
    if has_cs:
        operands.append(comp_state)
        in_specs.append(cs_spec)
        out_specs.append(cs_spec)
    if telemetry:
        operands += [grads, old_params]
        in_specs += [pspecs, pspecs]
        out_specs.append(IG.TelemetrySnapshot(
            *([buf_spec] * len(IG.FIELDS))))

    def body(*args):
        it = iter(args)
        p_shard, step_s = next(it), next(it)
        cs_l = strip_cs(next(it)) if has_cs else None
        g_l = strip_p(next(it)) if telemetry else None
        o_l = strip_p(next(it)) if telemetry else None
        local = strip_p(p_shard)
        mixed, cs_new, diag = S._communicate_c(
            local, comm, gossip_axis, topo, sched, step_s, None, None,
            "xla", fuse, bucket, cfg, cs_l, fusion_groups=groups,
            gossip_kernel=gk, interleave=il, kernel_mesh_axes=kmesh)
        outs = [wrap_p(mixed)]
        if has_cs:
            outs.append(wrap_cs(cs_new))
        if telemetry:
            col, row = IG.mix_mass(comm, gossip_axis, topo, sched, step_s)
            snap = IG.strategy_snapshot(
                step=step_s, new_params=mixed, old_params=o_l, grads=g_l,
                axis_name=S._telemetry_axis(comm, gossip_axis, None,
                                            gossip_axis=gossip_axis),
                col_sum=col, row_sum=row, fuse=fuse, bucket_bytes=bucket,
                sum_axis=inner,
                leaf_weights=_consensus_leaf_weights(inner_specs, mesh,
                                                     inner),
                **S._comp_snap_kwargs(diag))
            outs.append(wrap_b(snap))
        return tuple(outs)

    entry = _cached_program(
        ("mix", id(mesh), gossip_axis, _specs_key(inner_specs),
         id(topo), id(sched), fuse, bucket,
         None if cfg is None else cfg.spec,
         None if comp_state is None
         else jax.tree.structure(comp_state), telemetry, gk, il),
        lambda: jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                              out_specs=tuple(out_specs),
                              **_kernel_smap_kwargs(gk)))
    res = list(_pick_program(entry, operands)(*operands))
    mixed = res.pop(0)
    cs_new = res.pop(0) if has_cs else None
    snap = res.pop(0) if telemetry else None
    return mixed, cs_new, snap


def sharded_delayed_mix(adapted, step, inflight, *, mesh: Mesh,
                        inner_specs, gossip_axis: str = "dp", topo=None,
                        sched=None, fuse=None, fusion_bucket_bytes=None,
                        compression=None, comp_state=None,
                        telemetry: bool = False, grads=None,
                        old_params=None, gossip_kernel=None,
                        interleave=None):
    """Overlapped (staleness-1) flavor of :func:`sharded_neighbor_mix`:
    fold the PREVIOUS step's in-flight neighbor sum into ``adapted`` and
    launch this step's exchange on it (the ``strategies.delayed_atc_step``
    pipeline, per fsdp cell over the gossip axis).  ``inflight`` is the
    carried state from :func:`hybrid_inflight_state` / the previous call.

    ``gossip_kernel``/``interleave`` fuse each cell's launch leg exactly
    as in :func:`sharded_neighbor_mix` (CHOCO stays rejected under
    overlap by ``check_supported`` — only the EF-residual codecs ride
    the kernel here).

    Returns ``(combined, inflight_new, new_comp_state, snapshot)``.
    Traced-program caching as in :func:`sharded_neighbor_mix`."""
    from ..compress import compressors as CP
    from ..compress import exchange as CX
    from ..observability import ingraph as IG
    from ..optim import strategies as S
    from ..ops import fusion as F

    if (topo is None) == (sched is None):
        raise ValueError("pass exactly one of topo= or sched=")
    cfg = CP.resolve_compression(compression)
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    CX.check_supported(cfg, comm_value="neighbor.allreduce", sched=sched,
                       overlap=True)
    gk, auto_il = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value="neighbor.allreduce", fuse=fuse)
    il = auto_il if interleave is None else bool(interleave)
    kmesh = tuple(mesh.axis_names) if gk is not None else None
    if CX.stateful(cfg) and comp_state is None:
        raise ValueError(
            "stateful compression needs comp_state= (create it with "
            "compress.exchange.sharded_state_layout)")
    if telemetry and (grads is None or old_params is None):
        raise ValueError("telemetry=True needs grads= and old_params=")
    comm = S.CommunicationType.neighbor_allreduce
    (inner, groups, pspecs, buf_spec, strip_p, wrap_p, strip_b, wrap_b,
     cs_spec, strip_cs, wrap_cs) = _hybrid_plumbing(
        mesh, gossip_axis, inner_specs, comp_state, fuse)
    step = jnp.asarray(step, jnp.int32)
    if fuse:
        bufs_spec = jax.tree.map(lambda _: buf_spec, inflight["bufs"])
        strip_bufs, wrap_bufs = strip_b, wrap_b
    else:
        bufs_spec = pspecs
        strip_bufs, wrap_bufs = strip_p, wrap_p
    infl_spec = {"bufs": bufs_spec, "self_w": buf_spec}

    has_cs = comp_state is not None    # body must not pin the buffers
    operands = [adapted, step, inflight]
    in_specs = [pspecs, P(), infl_spec]
    out_specs = [pspecs, infl_spec]
    if has_cs:
        operands.append(comp_state)
        in_specs.append(cs_spec)
        out_specs.append(cs_spec)
    if telemetry:
        operands += [grads, old_params]
        in_specs += [pspecs, pspecs]
        out_specs.append(IG.TelemetrySnapshot(
            *([buf_spec] * len(IG.FIELDS))))

    def body(*args):
        it = iter(args)
        z_shard, step_s, infl_shard = next(it), next(it), next(it)
        cs_l = strip_cs(next(it)) if has_cs else None
        g_l = strip_p(next(it)) if telemetry else None
        o_l = strip_p(next(it)) if telemetry else None
        local_z = strip_p(z_shard)
        infl_l = {"bufs": strip_bufs(infl_shard["bufs"]),
                  "self_w": strip_b(infl_shard["self_w"])}
        combined = S._delayed_fold(local_z, infl_l, fuse, bucket, groups)
        launch = S._delayed_launch(
            local_z, comm, gossip_axis, topo, sched, step_s, None, None,
            "xla", fuse, bucket, cfg, cs_l, fusion_groups=groups,
            gossip_kernel=gk, interleave=il, kernel_mesh_axes=kmesh)
        infl_new, cs_new, diag = (launch if cfg is not None
                                  else (launch, None, None))
        outs = [wrap_p(combined),
                {"bufs": wrap_bufs(infl_new["bufs"]),
                 "self_w": wrap_b(infl_new["self_w"])}]
        if has_cs:
            outs.append(wrap_cs(cs_new))
        if telemetry:
            col, row = IG.mix_mass(comm, gossip_axis, topo, sched, step_s)
            warmup = (infl_l["self_w"] >= 1.0).astype(jnp.float32)
            snap = IG.strategy_snapshot(
                step=step_s, new_params=combined, old_params=o_l,
                grads=g_l,
                axis_name=S._telemetry_axis(comm, gossip_axis, None,
                                            gossip_axis=gossip_axis),
                col_sum=col, row_sum=row, fuse=fuse, bucket_bytes=bucket,
                staleness=1.0, warmup=warmup, sum_axis=inner,
                leaf_weights=_consensus_leaf_weights(inner_specs, mesh,
                                                     inner),
                **S._comp_snap_kwargs(diag))
            outs.append(wrap_b(snap))
        return tuple(outs)

    entry = _cached_program(
        ("delayed", id(mesh), gossip_axis, _specs_key(inner_specs),
         id(topo), id(sched), fuse, bucket,
         None if cfg is None else cfg.spec,
         None if comp_state is None
         else jax.tree.structure(comp_state),
         jax.tree.structure(inflight), telemetry, gk, il),
        lambda: jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                              out_specs=tuple(out_specs),
                              **_kernel_smap_kwargs(gk)))
    res = list(_pick_program(entry, operands)(*operands))
    combined = res.pop(0)
    infl_new = res.pop(0)
    cs_new = res.pop(0) if has_cs else None
    snap = res.pop(0) if telemetry else None
    return combined, infl_new, cs_new, snap


def make_decentralized_sharded_lm_train_step(
        model, base_opt: optax.GradientTransformation, mesh: Mesh,
        inner_specs_fn, topo=None, sched=None, donate: bool = True,
        fuse=None, fusion_bucket_bytes=None, overlap=None,
        compression=None, telemetry=None, gossip_axis: str = "dp",
        gossip_kernel=None):
    """Shared core of the decentralized-dp x {tp, fsdp} compositions.

    ``inner_specs_fn(params_single) -> spec tree`` supplies the
    within-replica shardings (Megatron rules for x tp, largest-divisible
    -dim ZeRO specs for x fsdp); the builder adds the leading ``dp``
    replica axis, places/pins params AND mirror optimizer state, runs the
    reference CTA step per replica, and neighbor-averages the parameter
    shards over ``dp`` through the unified comm hot path
    (:func:`sharded_neighbor_mix`).

    The optimized stack's knobs all work on the 2-level mesh and are
    resolved at build time (env fallbacks as everywhere else):

    * ``fuse``/``fusion_bucket_bytes`` — flat dtype buckets built over
      the SHARD shapes (``ops/fusion.py::shard_plan_for``); default on.
    * ``compression`` — the codec encodes each cell's 1/fsdp bucket
      slice; stateful configs (error-feedback residuals, CHOCO
      estimates) store their buffers SHARDED in the donated opt state,
      which becomes ``{"base": ..., "compress": ...}``.
    * ``overlap`` — the staleness-1 delayed-mix pipeline
      (:func:`sharded_delayed_mix`); adds ``{"inflight": ...}`` to the
      state.  Choco + overlap is rejected, as in ``optim/strategies``.
    * ``telemetry`` — the step returns ``(params, state, loss,
      TelemetrySnapshot)`` with per-cell ``[dp, fsdp]`` fields; consensus
      pmeans over the GOSSIP axis only (squared sums over fsdp).
    * ``gossip_kernel`` — fuse each cell's compressed bucket exchange
      into one kernel per bucket (``BLUEFOG_GOSSIP_KERNEL`` fallback,
      resolved/fail-fast at build via
      ``compress.exchange.effective_gossip_kernel``); the kernel's RDMAs
      address the neighbor replica's matching cell by mesh coordinates,
      so wire traffic stays the compressed 1/fsdp shard slice.

    With every knob off the lowered StableHLO is byte-identical to the
    pre-hybrid per-leaf path, and the plain ``opt_state`` layout is
    unchanged.  All per-step quantities (step index, dynamic-schedule
    edges, compression keys) are traced data — zero recompiles, asserted
    in ``tests/test_hybrid.py``.
    """
    from ..compress import compressors as CP
    from ..compress import exchange as CX
    from ..observability import ingraph as IG
    from ..optim import strategies as S
    from ..ops import fusion as F

    if (topo is None) == (sched is None):
        raise ValueError("pass exactly one of topo= or sched=")
    dp = mesh.shape[gossip_axis]
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    overlap = S.overlap_enabled(overlap)
    telemetry = IG.telemetry_enabled(telemetry)
    cfg = CP.resolve_compression(compression)
    CX.check_supported(cfg, comm_value="neighbor.allreduce", sched=sched,
                       overlap=overlap)
    comp_stateful = CX.stateful(cfg)
    dict_state = overlap or comp_stateful
    # snapshot: False = "off" even if the env changes before first trace
    comp_knob = cfg if cfg is not None else False
    # resolve the kernel knob at BUILD time too: bad combos fail here,
    # not at step 1, and later env flips can't retrace the step
    gk_mode, gk_il = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value="neighbor.allreduce", fuse=fuse)
    gk_knob = gk_mode if gk_mode is not None else False

    def _dp_specs(params):
        inner = inner_specs_fn(jax.tree.map(lambda a: a[0], params))
        return jax.tree.map(lambda spec: P(gossip_axis, *spec), inner,
                            is_leaf=lambda x: isinstance(x, P))

    def place(params_single):
        """Tile a single-replica params tree to [dp, ...] and shard it;
        returns freshly initialized (and identically sharded) per-replica
        optimizer state — wrapped as ``{"base": ...}`` plus the carried
        in-flight / compression buffers when overlap or stateful
        compression reshape the state layout."""
        gparams = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (dp,) + a.shape),
            params_single)
        specs = jax.tree.map(_norm_spec, _dp_specs(gparams),
                             is_leaf=lambda x: isinstance(x, P))
        gparams = jax.tree.map(
            lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
            gparams, specs)
        gopt = jax.jit(jax.vmap(base_opt.init))(gparams)
        gopt = _shard_like(gopt, gparams, mesh, specs=specs)
        if not dict_state:
            return gparams, gopt
        ispecs = inner_specs_fn(params_single)
        state = {"base": gopt}
        if overlap:
            state["inflight"] = hybrid_inflight_state(
                params_single, ispecs, mesh, gossip_axis=gossip_axis,
                fuse=fuse, fusion_bucket_bytes=bucket)
        if comp_stateful:
            state["compress"] = CX.sharded_state_layout(
                cfg, params_single, ispecs, mesh, gossip_axis=gossip_axis,
                fuse=fuse, bucket_bytes=bucket)
        return gparams, state

    def _loss(p, tokens, targets):
        def one(p_, tok, tgt):
            logits = model.apply({"params": p_}, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()
        return jax.vmap(one)(p, tokens, targets)     # [dp] per-replica loss

    def _constrain(tree, specs):
        return jax.tree.map(
            lambda leaf, spec: jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, spec)), tree, specs)

    def step_fn(params, opt_state, tokens, targets, step=0):
        step = jnp.asarray(step, jnp.int32)
        specs = _dp_specs(params)

        def mean_loss(p):
            return _loss(p, tokens, targets).mean()

        loss, grads = jax.value_and_grad(mean_loss)(params)
        # mean over dp scales every replica's grad by 1/dp — undo so each
        # replica applies ITS OWN full gradient (reference CTA semantics)
        grads = jax.tree.map(lambda g: g * dp, grads)
        grads = _constrain(grads, specs)
        bs = opt_state["base"] if dict_state else opt_state
        updates, bs_new = jax.vmap(base_opt.update)(grads, bs, params)
        # pin the updated optimizer state: mirror subtrees must come out
        # with the parameter shardings, or the state memory saving is
        # lost and step 2 recompiles (breaking donation)
        bs_new = _constrain(bs_new, _mirror_specs(bs_new, params, specs))
        adapted = optax.apply_updates(params, updates)
        ispecs = inner_specs_fn(jax.tree.map(lambda a: a[0], params))
        cs = opt_state.get("compress") if comp_stateful else None
        if overlap:
            new_params, infl_new, cs_new, snap = sharded_delayed_mix(
                adapted, step, opt_state["inflight"], mesh=mesh,
                inner_specs=ispecs, gossip_axis=gossip_axis, topo=topo,
                sched=sched, fuse=fuse, fusion_bucket_bytes=bucket,
                compression=comp_knob, comp_state=cs,
                telemetry=telemetry, grads=grads, old_params=params,
                gossip_kernel=gk_knob, interleave=gk_il)
            out_state = {"base": bs_new, "inflight": infl_new}
        else:
            new_params, cs_new, snap = sharded_neighbor_mix(
                adapted, step, mesh=mesh, inner_specs=ispecs,
                gossip_axis=gossip_axis, topo=topo, sched=sched,
                fuse=fuse, fusion_bucket_bytes=bucket,
                compression=comp_knob, comp_state=cs,
                telemetry=telemetry, grads=grads, old_params=params,
                gossip_kernel=gk_knob, interleave=gk_il)
            out_state = {"base": bs_new} if dict_state else bs_new
        if comp_stateful:
            out_state["compress"] = cs_new
        if telemetry:
            return new_params, out_state, loss, snap
        return new_params, out_state, loss

    jitted = jax.jit(step_fn, donate_argnums=(0, 1) if donate else ())
    return jitted, place


def _mirror_specs(opt_state, params, specs):
    """PartitionSpec tree for an optimizer state: subtrees that mirror the
    params tree structure (optax mu/nu/trace are exact structural copies)
    get the parameter specs; everything else replicates.  Structural
    matching — never by shape, which is ambiguous when two params share
    one shape."""
    pstruct = jax.tree.structure(params)

    def is_mirror(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:
            return False

    def spec_tree(node):
        if is_mirror(node):
            return specs
        return jax.tree.map(lambda _: P(), node)

    return jax.tree_util.tree_map(spec_tree, opt_state, is_leaf=is_mirror)


def _shard_like(opt_state, params, mesh, tp_axis: str = "tp", specs=None):
    """Place an optimizer state with the mirror-matching policy of
    :func:`_mirror_specs` (``specs`` overrides the TP rules — parallel/fsdp
    passes its own)."""
    if specs is None:
        specs = transformer_tp_rules(params, tp_axis)
    spec_tree = _mirror_specs(opt_state, params, specs)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        opt_state, spec_tree)
