"""Tensor parallelism: Megatron-style sharding rules via GSPMD.

No reference counterpart (SURVEY.md §2.6: TP absent in BlueFog — "no weight
sharding anywhere"); built because weight sharding is a core TPU scaling
axis.  The idiomatic TPU implementation is *declarative*: place parameter
leaves with ``NamedSharding`` over a ``(dp, tp)`` mesh and let XLA's SPMD
partitioner insert the all-gathers/reduce-scatters — no hand-written
collectives (the How-to-Scale-Your-Model recipe: pick a mesh, annotate
shardings, let XLA do the rest).

Rules follow the Megatron pattern for the Transformer family
(``models/transformer.py``):

  * qkv projection: split the heads dimension (column parallel)
  * attention output projection: split the heads dimension (row parallel)
  * MLP up: split the hidden dimension (column), MLP down: row
  * MoE experts: split the expert dimension
  * embeddings / norms / router: replicated over tp

Gradients and optimizer states inherit the parameter shardings through
jit's sharding propagation, so the Adam mirror of a sharded weight is
sharded identically for free.
"""

import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["transformer_tp_rules", "shard_params", "make_tp_lm_train_step",
           "tp_mesh"]

# (path regex, PartitionSpec factory given tp axis name); first match wins
_TP_RULES = [
    (r"qkv/kernel$",      lambda tp: P(None, None, tp, None)),  # [D,3,H,hd]
    (r"qkv/bias$",        lambda tp: P(None, tp, None)),        # [3,H,hd]
    (r"proj/kernel$",     lambda tp: P(tp, None, None)),        # [H,hd,D]
    (r"mlp_up/kernel$",   lambda tp: P(None, tp)),              # [D,Hm]
    (r"mlp_up/bias$",     lambda tp: P(tp)),                    # [Hm]
    (r"mlp_down/kernel$", lambda tp: P(tp, None)),              # [Hm,D]
    (r"moe/w_up$",        lambda tp: P(tp, None, None)),        # [E,D,Hm]
    (r"moe/b_up$",        lambda tp: P(tp, None)),
    (r"moe/w_down$",      lambda tp: P(tp, None, None)),
    (r"moe/b_down$",      lambda tp: P(tp, None)),
    (r"lm_head/kernel$",  lambda tp: P(None, tp)),              # [D,V]
    (r"lm_head/bias$",    lambda tp: P(tp)),
]


def transformer_tp_rules(params, tp_axis: str = "tp"):
    """PartitionSpec pytree for a Transformer params tree (unmatched leaves
    replicate)."""
    def spec_for(path, leaf):
        name = jax.tree_util.keystr(path, simple=True, separator="/")
        for pat, mk in _TP_RULES:
            if re.search(pat, name):
                spec = mk(tp_axis)
                if len(spec) <= leaf.ndim:
                    return spec
        return P()
    return jax.tree_util.tree_map_with_path(spec_for, params)


def tp_mesh(dp: int, tp: int, devices=None) -> Mesh:
    """A ``(dp, tp)`` mesh; tp should map to the fastest (ICI-adjacent)
    axis, which is the trailing one in the device array."""
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[: dp * tp])
    if devices.size != dp * tp:
        raise ValueError(f"need {dp * tp} devices, have {devices.size}")
    return Mesh(devices.reshape(dp, tp), ("dp", "tp"))


def shard_params(params, mesh: Mesh, tp_axis: str = "tp"):
    """Place a replicated params tree according to the TP rules."""
    specs = transformer_tp_rules(params, tp_axis)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs)


def make_tp_lm_train_step(model, base_opt: optax.GradientTransformation,
                          mesh: Mesh, donate: bool = True):
    """Data+tensor-parallel LM train step on a ``(dp, tp)`` mesh.

    Tokens/targets ``[B, T]`` are batch-sharded over ``dp``; parameters are
    sharded by :func:`transformer_tp_rules` over ``tp``.  The step is a
    plain jitted ``value_and_grad`` — XLA's partitioner derives every
    collective (all-gather of column-parallel outputs, psum of row-parallel
    partials, gradient reduce-scatter) from the in/out shardings.

    Returns ``(step_fn, place_fn)``: ``place_fn(params, opt_state)`` puts a
    freshly initialized state onto the mesh; ``step_fn(params, opt_state,
    tokens, targets) -> (params, opt_state, loss)``.
    """
    data_sharding = NamedSharding(mesh, P("dp", None))

    def place(params, opt_state):
        params = shard_params(params, mesh)
        return params, _shard_like(opt_state, params, mesh)

    def _loss(p, tokens, targets):
        logits = model.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    @jax.jit
    def step(params, opt_state, tokens, targets):
        tokens = jax.lax.with_sharding_constraint(tokens, data_sharding)
        targets = jax.lax.with_sharding_constraint(targets, data_sharding)
        loss, grads = jax.value_and_grad(_loss)(params, tokens, targets)
        updates, opt_state = base_opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    if donate:
        step = jax.jit(step.__wrapped__, donate_argnums=(0, 1))
    return step, place


def _shard_like(opt_state, params, mesh, tp_axis: str = "tp"):
    """Shard optimizer-state subtrees that mirror the params tree structure
    (optax mu/nu/trace are exact structural copies) with the parameter
    specs; everything else replicates.  Structural matching — never by
    shape, which is ambiguous when two params share one shape."""
    specs = transformer_tp_rules(params, tp_axis)
    pstruct = jax.tree.structure(params)

    def is_mirror(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:
            return False

    def place(node):
        if is_mirror(node):
            return jax.tree.map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)), node, specs)
        return jax.tree.map(
            lambda leaf: jax.device_put(leaf, NamedSharding(mesh, P())),
            node)

    return jax.tree_util.tree_map(place, opt_state, is_leaf=is_mirror)
