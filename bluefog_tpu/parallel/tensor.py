"""Tensor parallelism: Megatron-style sharding rules via GSPMD.

No reference counterpart (SURVEY.md §2.6: TP absent in BlueFog — "no weight
sharding anywhere"); built because weight sharding is a core TPU scaling
axis.  The idiomatic TPU implementation is *declarative*: place parameter
leaves with ``NamedSharding`` over a ``(dp, tp)`` mesh and let XLA's SPMD
partitioner insert the all-gathers/reduce-scatters — no hand-written
collectives (the How-to-Scale-Your-Model recipe: pick a mesh, annotate
shardings, let XLA do the rest).

Rules follow the Megatron pattern for the Transformer family
(``models/transformer.py``):

  * qkv projection: split the heads dimension (column parallel)
  * attention output projection: split the heads dimension (row parallel)
  * MLP up: split the hidden dimension (column), MLP down: row
  * MoE experts: split the expert dimension
  * embeddings / norms / router: replicated over tp

Gradients and optimizer states inherit the parameter shardings through
jit's sharding propagation, so the Adam mirror of a sharded weight is
sharded identically for free.
"""

import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["transformer_tp_rules", "shard_params", "make_tp_lm_train_step",
           "make_decentralized_tp_lm_train_step",
           "make_decentralized_sharded_lm_train_step", "tp_mesh"]

# (path regex, PartitionSpec factory given tp axis name); first match wins
_TP_RULES = [
    (r"qkv/kernel$",      lambda tp: P(None, None, tp, None)),  # [D,3,H,hd]
    (r"qkv/bias$",        lambda tp: P(None, tp, None)),        # [3,H,hd]
    (r"proj/kernel$",     lambda tp: P(tp, None, None)),        # [H,hd,D]
    (r"mlp_up/kernel$",   lambda tp: P(None, tp)),              # [D,Hm]
    (r"mlp_up/bias$",     lambda tp: P(tp)),                    # [Hm]
    (r"mlp_down/kernel$", lambda tp: P(tp, None)),              # [Hm,D]
    (r"moe/w_up$",        lambda tp: P(tp, None, None)),        # [E,D,Hm]
    (r"moe/b_up$",        lambda tp: P(tp, None)),
    (r"moe/w_down$",      lambda tp: P(tp, None, None)),
    (r"moe/b_down$",      lambda tp: P(tp, None)),
    (r"lm_head/kernel$",  lambda tp: P(None, tp)),              # [D,V]
    (r"lm_head/bias$",    lambda tp: P(tp)),
]


def transformer_tp_rules(params, tp_axis: str = "tp"):
    """PartitionSpec pytree for a Transformer params tree (unmatched leaves
    replicate)."""
    def spec_for(path, leaf):
        name = jax.tree_util.keystr(path, simple=True, separator="/")
        for pat, mk in _TP_RULES:
            if re.search(pat, name):
                spec = mk(tp_axis)
                if len(spec) <= leaf.ndim:
                    return spec
        return P()
    return jax.tree_util.tree_map_with_path(spec_for, params)


def tp_mesh(dp: int, tp: int, devices=None) -> Mesh:
    """A ``(dp, tp)`` mesh; tp should map to the fastest (ICI-adjacent)
    axis, which is the trailing one in the device array."""
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[: dp * tp])
    if devices.size != dp * tp:
        raise ValueError(f"need {dp * tp} devices, have {devices.size}")
    return Mesh(devices.reshape(dp, tp), ("dp", "tp"))


def shard_params(params, mesh: Mesh, tp_axis: str = "tp"):
    """Place a replicated params tree according to the TP rules."""
    specs = transformer_tp_rules(params, tp_axis)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs)


def make_tp_lm_train_step(model, base_opt: optax.GradientTransformation,
                          mesh: Mesh, donate: bool = True):
    """Data+tensor-parallel LM train step on a ``(dp, tp)`` mesh.

    Tokens/targets ``[B, T]`` are batch-sharded over ``dp``; parameters are
    sharded by :func:`transformer_tp_rules` over ``tp``.  The step is a
    plain jitted ``value_and_grad`` — XLA's partitioner derives every
    collective (all-gather of column-parallel outputs, psum of row-parallel
    partials, gradient reduce-scatter) from the in/out shardings.

    Returns ``(step_fn, place_fn)``: ``place_fn(params, opt_state)`` puts a
    freshly initialized state onto the mesh; ``step_fn(params, opt_state,
    tokens, targets) -> (params, opt_state, loss)``.
    """
    data_sharding = NamedSharding(mesh, P("dp", None))

    def place(params, opt_state):
        params = shard_params(params, mesh)
        return params, _shard_like(opt_state, params, mesh)

    def _loss(p, tokens, targets):
        logits = model.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    @jax.jit
    def step(params, opt_state, tokens, targets):
        tokens = jax.lax.with_sharding_constraint(tokens, data_sharding)
        targets = jax.lax.with_sharding_constraint(targets, data_sharding)
        loss, grads = jax.value_and_grad(_loss)(params, tokens, targets)
        updates, opt_state = base_opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    if donate:
        step = jax.jit(step.__wrapped__, donate_argnums=(0, 1))
    return step, place


def make_decentralized_tp_lm_train_step(
        model, base_opt: optax.GradientTransformation, mesh: Mesh,
        topo=None, sched=None, donate: bool = True):
    """Decentralized DP composed with TP on ONE ``(dp, tp)`` mesh.

    The framework's flagship composition (VERDICT r1 item 7): the ``dp``
    axis runs BlueFog-style *neighbor averaging of parameters* (static
    ``topo``, a :class:`~bluefog_tpu.parallel.schedule.CompiledTopology`, or
    dynamic ``sched`` selected by the traced step index) while ``tp``
    Megatron-shards every replica.  One jitted program: each replica's
    forward/backward/update is GSPMD-partitioned over ``tp`` (XLA inserts
    the all-gathers/psums from the sharding rules), and the decentralized
    exchange is a ``shard_map`` whose body ppermutes each ``(dp, tp)``
    cell's *parameter shard* over the ``dp`` axis — mixing is elementwise,
    so each tp cell exchanges only its own 1/tp of the weights (the
    composition is bandwidth-optimal, not an afterthought).

    Parameter leaves carry a leading replica axis: [dp, *param_shape],
    sharded ``P("dp", *tp_rule)``.  Returns ``(step_fn, place_fn)`` with
    ``step_fn(params, opt_state, tokens, targets, step) -> (params,
    opt_state, loss)``; ``tokens``/``targets`` are [dp, B_local, T].
    """
    return make_decentralized_sharded_lm_train_step(
        model, base_opt, mesh, transformer_tp_rules,
        topo=topo, sched=sched, donate=donate)


def make_decentralized_sharded_lm_train_step(
        model, base_opt: optax.GradientTransformation, mesh: Mesh,
        inner_specs_fn, topo=None, sched=None, donate: bool = True):
    """Shared core of the decentralized-dp x {tp, fsdp} compositions.

    ``inner_specs_fn(params_single) -> spec tree`` supplies the
    within-replica shardings (Megatron rules for x tp, largest-divisible
    -dim ZeRO specs for x fsdp); the builder adds the leading ``dp``
    replica axis, places/pins params AND mirror optimizer state, runs the
    reference CTA step per replica, and neighbor-averages the parameter
    shards over ``dp`` inside a shard_map.
    """
    from ..ops import collectives as C

    if (topo is None) == (sched is None):
        raise ValueError("pass exactly one of topo= or sched=")
    dp = mesh.shape["dp"]

    def _dp_specs(params):
        inner = inner_specs_fn(jax.tree.map(lambda a: a[0], params))
        return jax.tree.map(lambda spec: P("dp", *spec), inner,
                            is_leaf=lambda x: isinstance(x, P))

    def place(params_single):
        """Tile a single-replica params tree to [dp, ...] and shard it;
        returns freshly initialized (and identically sharded) per-replica
        optimizer state."""
        gparams = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (dp,) + a.shape),
            params_single)
        specs = _dp_specs(gparams)
        gparams = jax.tree.map(
            lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
            gparams, specs)
        gopt = jax.jit(jax.vmap(base_opt.init))(gparams)
        return gparams, _shard_like(gopt, gparams, mesh, specs=specs)

    def _loss(p, tokens, targets):
        def one(p_, tok, tgt):
            logits = model.apply({"params": p_}, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()
        return jax.vmap(one)(p, tokens, targets)     # [dp] per-replica loss

    def _mix(params, step):
        """Decentralized neighbor averaging over the dp axis, per cell."""
        specs = _dp_specs(params)

        def body(p_shard, step_s):
            def mix_leaf(a):
                x = a[0]                                 # strip local dp dim
                if sched is not None:
                    return C.dynamic_neighbor_allreduce(
                        x, "dp", sched, step_s)[None]
                return C.neighbor_allreduce(x, "dp", topo)[None]
            return jax.tree.map(mix_leaf, p_shard)

        return jax.shard_map(
            body, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        )(params, step)

    def _constrain(tree, specs):
        return jax.tree.map(
            lambda leaf, spec: jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, spec)), tree, specs)

    def step_fn(params, opt_state, tokens, targets, step=0):
        step = jnp.asarray(step, jnp.int32)
        specs = _dp_specs(params)

        def mean_loss(p):
            return _loss(p, tokens, targets).mean()

        loss, grads = jax.value_and_grad(mean_loss)(params)
        # mean over dp scales every replica's grad by 1/dp — undo so each
        # replica applies ITS OWN full gradient (reference CTA semantics)
        grads = jax.tree.map(lambda g: g * dp, grads)
        grads = _constrain(grads, specs)
        updates, opt_state = jax.vmap(base_opt.update)(grads, opt_state,
                                                       params)
        # pin the updated optimizer state: mirror subtrees must come out
        # with the parameter shardings, or the state memory saving is
        # lost and step 2 recompiles (breaking donation)
        opt_state = _constrain(opt_state,
                               _mirror_specs(opt_state, params, specs))
        params = optax.apply_updates(params, updates)
        params = _mix(params, step)
        return params, opt_state, loss

    jitted = jax.jit(step_fn, donate_argnums=(0, 1) if donate else ())
    return jitted, place


def _mirror_specs(opt_state, params, specs):
    """PartitionSpec tree for an optimizer state: subtrees that mirror the
    params tree structure (optax mu/nu/trace are exact structural copies)
    get the parameter specs; everything else replicates.  Structural
    matching — never by shape, which is ambiguous when two params share
    one shape."""
    pstruct = jax.tree.structure(params)

    def is_mirror(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:
            return False

    def spec_tree(node):
        if is_mirror(node):
            return specs
        return jax.tree.map(lambda _: P(), node)

    return jax.tree_util.tree_map(spec_tree, opt_state, is_leaf=is_mirror)


def _shard_like(opt_state, params, mesh, tp_axis: str = "tp", specs=None):
    """Place an optimizer state with the mirror-matching policy of
    :func:`_mirror_specs` (``specs`` overrides the TP rules — parallel/fsdp
    passes its own)."""
    if specs is None:
        specs = transformer_tp_rules(params, tp_axis)
    spec_tree = _mirror_specs(opt_state, params, specs)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        opt_state, spec_tree)
