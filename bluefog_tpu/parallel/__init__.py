"""Parallelism layer: topologies, dynamic schedules, mesh/collective plans."""

from . import topology, dynamic, schedule

# tensor/pipeline pull in flax; defer them (PEP 562) so collective-only
# users of the package never pay the import
_LAZY = {
    "tensor": ("tensor", None),
    "pipeline": ("pipeline", None),
    "make_tp_lm_train_step": ("tensor", "make_tp_lm_train_step"),
    "shard_params": ("tensor", "shard_params"),
    "tp_mesh": ("tensor", "tp_mesh"),
    "transformer_tp_rules": ("tensor", "transformer_tp_rules"),
    "make_pp_lm_train_step": ("pipeline", "make_pp_lm_train_step"),
    "pp_mesh": ("pipeline", "pp_mesh"),
    "stack_block_params": ("pipeline", "stack_block_params"),
    "unstack_block_params": ("pipeline", "unstack_block_params"),
    "fsdp": ("fsdp", None),
    "make_fsdp_lm_train_step": ("fsdp", "make_fsdp_lm_train_step"),
    "fsdp_mesh": ("fsdp", "fsdp_mesh"),
    "fsdp_specs": ("fsdp", "fsdp_specs"),
    "shard_params_fsdp": ("fsdp", "shard_params_fsdp"),
    "make_decentralized_fsdp_lm_train_step":
        ("fsdp", "make_decentralized_fsdp_lm_train_step"),
    "dfsdp_mesh": ("fsdp", "dfsdp_mesh"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        modname, attr = _LAZY[name]
        mod = importlib.import_module(f".{modname}", __name__)
        return getattr(mod, attr) if attr else mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
