"""Parallelism layer: topologies, dynamic schedules, mesh/collective plans."""

from . import topology, dynamic, schedule
