"""FSDP / ZeRO-3-style fully-sharded data parallelism via GSPMD.

No reference counterpart (SURVEY.md §2.6: "FSDP/ZeRO sharding — NO");
built because it completes the TPU scaling matrix next to TP/PP/SP/EP:
parameters, gradients, and optimizer state are **sharded over the data
axis**, so per-chip state memory scales 1/N while the batch stays
data-parallel.

The idiomatic TPU implementation is declarative, like ``parallel/tensor``:
each parameter leaf is placed with a ``NamedSharding`` that splits its
largest divisible dimension over the ``dp`` axis, and XLA's SPMD
partitioner derives the ZeRO-3 schedule from the shardings alone — an
all-gather of each weight right before use (forward and again in the
backward), a reduce-scatter of its gradient, and a fully sharded optimizer
update, with no hand-written collectives.  Optimizer-state subtrees that
mirror the params tree (optax mu/nu/trace) inherit the same specs, which
is exactly the ZeRO-3 optimizer-state partition.

Composes with the model-side levers: ``TransformerLM(remat=True)`` trades
the gathered activations back for FLOPs, and the flash kernel keeps
attention O(T) — together the classic long-context/large-model recipe.
"""

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["fsdp_specs", "fsdp_mesh", "shard_params_fsdp",
           "make_fsdp_lm_train_step",
           "make_decentralized_fsdp_lm_train_step", "dfsdp_mesh"]


def fsdp_mesh(dp: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D ``("dp",)`` mesh over ``dp`` devices (default: all)."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if dp is not None:
        if devices.size < dp:
            raise ValueError(f"need {dp} devices, have {devices.size}")
        devices = devices[:dp]
    return Mesh(devices, ("dp",))


def _leaf_spec(leaf, n: int, axis: str) -> P:
    """Split the largest dimension divisible by ``n`` (ties -> lowest
    index); replicate leaves with no such dimension (scalars, norms,
    biases smaller than the mesh)."""
    dims = [(size, i) for i, size in enumerate(leaf.shape)
            if size % n == 0 and size >= n]
    if not dims:
        return P()
    _, best = max(dims, key=lambda t: (t[0], -t[1]))
    spec = [None] * leaf.ndim
    spec[best] = axis
    return P(*spec)


def fsdp_specs(params, mesh: Mesh, axis: str = "dp"):
    """PartitionSpec pytree: every leaf sharded over ``axis`` along its
    largest divisible dimension."""
    n = mesh.shape[axis]
    return jax.tree.map(lambda leaf: _leaf_spec(leaf, n, axis), params)


def shard_params_fsdp(params, mesh: Mesh, axis: str = "dp"):
    """Place a replicated params tree fully sharded over the mesh."""
    specs = fsdp_specs(params, mesh, axis)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs)


def make_fsdp_lm_train_step(model, base_opt: optax.GradientTransformation,
                            mesh: Mesh, donate: bool = True):
    """Fully-sharded data-parallel LM train step on a ``("dp",)`` mesh.

    Tokens/targets ``[B, T]`` are batch-sharded over ``dp``; every
    parameter / gradient / optimizer-state leaf is sharded by
    :func:`fsdp_specs`.  The step is a plain jitted ``value_and_grad``
    whose output shardings pin the updated state to the same specs, so
    XLA emits the ZeRO-3 schedule (per-weight all-gather at use,
    gradient reduce-scatter, sharded update) rather than replicating.

    Returns ``(step_fn, place_fn)``: ``place_fn(params, opt_state)``
    shards a freshly initialized state; ``step_fn(params, opt_state,
    tokens, targets) -> (params, opt_state, loss)``.
    """
    from .tensor import _mirror_specs, _shard_like

    data_sharding = NamedSharding(mesh, P("dp", None))

    def place(params, opt_state):
        specs = fsdp_specs(params, mesh)
        sharded = jax.tree.map(
            lambda leaf, spec: jax.device_put(
                leaf, NamedSharding(mesh, spec)), params, specs)
        return sharded, _shard_like(opt_state, params, mesh, specs=specs)

    def _loss(p, tokens, targets):
        logits = model.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    def _constrain(tree, specs):
        return jax.tree.map(
            lambda leaf, spec: jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, spec)), tree, specs)

    def step(params, opt_state, tokens, targets):
        specs = fsdp_specs(params, mesh)
        tokens = jax.lax.with_sharding_constraint(tokens, data_sharding)
        targets = jax.lax.with_sharding_constraint(targets, data_sharding)
        loss, grads = jax.value_and_grad(_loss)(params, tokens, targets)
        # pin gradients to the parameter shardings: this is the
        # reduce-scatter — without it XLA may all-reduce to replicated
        grads = _constrain(grads, specs)
        updates, opt_state = base_opt.update(grads, opt_state, params)
        new_params = _constrain(optax.apply_updates(params, updates), specs)
        # pin the optimizer state too: mu/nu must come out ZeRO-3-sharded,
        # or the state memory saving is lost and step 2 recompiles
        opt_state = _constrain(opt_state,
                               _mirror_specs(opt_state, params, specs))
        return new_params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ()), place


def dfsdp_mesh(dp: Optional[int] = None, fsdp: Optional[int] = None,
               devices=None) -> Mesh:
    """A ``(dp, fsdp)`` mesh: ``dp`` decentralized replicas, each fully
    sharded over ``fsdp`` ICI-adjacent chips (the trailing axis).

    ``fsdp=None`` reads ``BLUEFOG_MESH_FSDP`` (default 1 — pure
    decentralized DP); ``dp=None`` takes every remaining device.  A
    device list longer than ``dp * fsdp`` is TRIMMED, exactly like
    :func:`fsdp_mesh` (the pre-fix behavior raised instead, so
    ``dfsdp_mesh(2, 2)`` on an 8-device host failed while
    ``fsdp_mesh(4)`` worked — regression-tested in
    ``tests/test_fsdp.py``)."""
    if fsdp is None:
        fsdp = int(os.environ.get("BLUEFOG_MESH_FSDP", "1"))
    if fsdp <= 0:
        raise ValueError(f"fsdp must be positive, got {fsdp}")
    devices = np.asarray(devices if devices is not None
                         else jax.devices()).reshape(-1)
    if dp is None:
        dp = devices.size // fsdp
        if dp == 0:
            raise ValueError(
                f"need at least {fsdp} devices for fsdp={fsdp}, have "
                f"{devices.size}")
    need = dp * fsdp
    if devices.size < need:
        raise ValueError(f"need {need} devices, have {devices.size}")
    return Mesh(devices[:need].reshape(dp, fsdp), ("dp", "fsdp"))


def make_decentralized_fsdp_lm_train_step(
        model, base_opt: optax.GradientTransformation, mesh: Mesh,
        topo=None, sched=None, donate: bool = True, **comm_kwargs):
    """Decentralized DP composed with FSDP on ONE ``(dp, fsdp)`` mesh.

    Sibling of ``tensor.make_decentralized_tp_lm_train_step`` (same
    [dp, ...] global view, same reference CTA semantics, same shared
    builder), with ZeRO-3 sharding inside each replica instead of
    Megatron TP: the ``dp`` axis runs BlueFog-style neighbor averaging of
    parameters (static ``topo`` or dynamic ``sched``), while every
    replica's params / grads / optimizer state shard over ``fsdp``.
    Averaging is elementwise, so each (dp, fsdp) cell exchanges only its
    own 1/fsdp shard — the decentralized traffic shrinks with the
    sharding, exactly like the ×tp composition.

    The exchange runs through the unified comm hot path
    (``parallel/tensor.py::sharded_neighbor_mix``): ``comm_kwargs``
    accepts ``fuse=``/``fusion_bucket_bytes=`` (shard-shaped flat
    buckets), ``compression=`` (the codec encodes the 1/fsdp slice —
    multiplying this composition's wire win), ``overlap=`` (staleness-1
    delayed-mix pipeline), ``telemetry=`` (consensus over the dp
    gossip axis only) and ``gossip_kernel=`` (one fused kernel per
    compressed bucket per cell, RDMAs addressed by mesh coordinates);
    see ``docs/hybrid_scaleout.md``.

    Returns ``(step_fn, place_fn)`` with ``step_fn(params, opt_state,
    tokens, targets, step) -> (params, opt_state, loss)``;
    ``tokens``/``targets`` are [dp, B_local, T]; parameter leaves carry a
    leading replica axis [dp, *shape].
    """
    from .tensor import make_decentralized_sharded_lm_train_step
    return make_decentralized_sharded_lm_train_step(
        model, base_opt, mesh,
        lambda p: fsdp_specs(p, mesh, axis="fsdp"),
        topo=topo, sched=sched, donate=donate, **comm_kwargs)
