"""Virtual graph topologies for decentralized averaging.

Every generator returns a weighted ``networkx.DiGraph`` whose adjacency entry
``A[i, j]`` is the weight with which rank ``j`` mixes rank ``i``'s value, i.e.
the mixing step computes ``x_j <- sum_i A[i, j] * x_i`` (column-stochastic in
the usual decentralized-SGD notation).  Semantics match the reference
implementation (``bluefog/common/topology_util.py``) so that topology unit
tests and published weight schemes (Hastings rule, exponential-2, etc.) carry
over; the construction here is vectorized instead of row-by-row.

Reference parity map (reference file:line):
  * ExponentialTwoGraph        topology_util.py:66
  * ExponentialGraph           topology_util.py:99
  * SymmetricExponentialGraph  topology_util.py:128
  * MeshGrid2DGraph            topology_util.py:160  (Hastings weights)
  * StarGraph                  topology_util.py:214
  * RingGraph                  topology_util.py:240
  * FullyConnectedGraph        topology_util.py:284
  * IsTopologyEquivalent       topology_util.py:23
  * IsRegularGraph             topology_util.py:306
  * GetRecvWeights/SendWeights topology_util.py:40-63
"""

from typing import Dict, Optional, Tuple

import numpy as np
import networkx as nx

__all__ = [
    "ExponentialTwoGraph",
    "ExponentialGraph",
    "SymmetricExponentialGraph",
    "MeshGrid2DGraph",
    "StarGraph",
    "RingGraph",
    "FullyConnectedGraph",
    "IsTopologyEquivalent",
    "IsRegularGraph",
    "isPowerOf",
    "GetRecvWeights",
    "GetSendWeights",
    "mixing_matrix",
]


def _from_circulant_row(row: np.ndarray) -> nx.DiGraph:
    """Build a circulant digraph: ``A[i, j] = row[(j - i) mod n]``.

    ``row`` holds the weights a rank sends to offsets ``0..n-1`` ahead of it
    (offset 0 is the self loop).
    """
    n = row.shape[0]
    offsets = (np.arange(n)[None, :] - np.arange(n)[:, None]) % n
    return nx.from_numpy_array(row[offsets], create_using=nx.DiGraph)


def _normalized_indicator(mask: np.ndarray) -> np.ndarray:
    row = mask.astype(np.float64)
    return row / row.sum()


def _is_power_of(value: int, base: int) -> bool:
    """Exact integer check that ``value == base ** k`` for some integer k >= 0."""
    if not isinstance(base, int) or base <= 1:
        raise ValueError("base must be an integer larger than 1")
    if value <= 0:
        return False
    while value % base == 0:
        value //= base
    return value == 1


def mixing_matrix(topo: nx.DiGraph) -> np.ndarray:
    """Adjacency/weight matrix of a topology as a dense float64 array.

    ``W = mixing_matrix(G)`` satisfies ``x_new[j] = sum_i W[i, j] * x_old[i]``
    (i.e. column j holds rank j's receive weights).
    """
    return nx.to_numpy_array(topo)


def ExponentialTwoGraph(size: int) -> nx.DiGraph:
    """Each rank connects to ranks at distance 1, 2, 4, ... (powers of two).

    Uniform weights over the self loop and the log2(size) out-edges.
    """
    assert size > 0
    idx = np.arange(size)
    # offset 0 (self) or any exact power of two
    mask = (idx & (idx - 1)) == 0
    return _from_circulant_row(_normalized_indicator(mask))


def ExponentialGraph(size: int, base: int = 2) -> nx.DiGraph:
    """Each rank connects to ranks at offsets that are exact powers of ``base``."""
    assert size > 0
    mask = np.array(
        [i == 0 or _is_power_of(i, base) for i in range(size)], dtype=bool
    )
    return _from_circulant_row(_normalized_indicator(mask))


def SymmetricExponentialGraph(size: int, base: int = 4) -> nx.DiGraph:
    """Exponential graph whose offsets beyond size//2 mirror the first half."""
    assert size > 0
    folded = [0] + [i if i <= size // 2 else size - i for i in range(1, size)]
    mask = np.array(
        [i == 0 or _is_power_of(f, base) for i, f in enumerate(folded)], dtype=bool
    )
    return _from_circulant_row(_normalized_indicator(mask))


def MeshGrid2DGraph(size: int, shape: Optional[Tuple[int, int]] = None) -> nx.DiGraph:
    """2-D mesh-grid graph with Metropolis–Hastings weights.

    When ``shape`` is omitted the grid uses the two closest factors of
    ``size`` (rows <= cols); a prime size degrades to a line.  Off-diagonal
    weights follow the Hastings rule ``1 / max(deg_i, deg_j)`` with degrees
    counted *including* the self loop; the self weight absorbs the remainder
    so each row sums to one.
    """
    assert size > 0
    if shape is None:
        nrow = int(np.sqrt(size))
        while size % nrow != 0:
            nrow -= 1
        shape = (nrow, size // nrow)
    nrow, ncol = shape
    if nrow * ncol != size:
        raise ValueError(f"shape {shape} does not match size {size}")

    adj = np.eye(size, dtype=bool)
    for i in range(size):
        if (i + 1) % ncol != 0:  # right neighbor within the same row
            adj[i, i + 1] = adj[i + 1, i] = True
        if i + ncol < size:  # neighbor in the next row
            adj[i, i + ncol] = adj[i + ncol, i] = True

    degree = adj.sum(axis=1)  # includes self
    weights = np.zeros((size, size))
    pair_deg = np.maximum(degree[:, None], degree[None, :])
    off = adj & ~np.eye(size, dtype=bool)
    weights[off] = 1.0 / pair_deg[off]
    np.fill_diagonal(weights, 1.0 - weights.sum(axis=1))
    return nx.from_numpy_array(weights, create_using=nx.DiGraph)


def StarGraph(size: int, center_rank: int = 0) -> nx.DiGraph:
    """Bidirectional star: every rank exchanges with ``center_rank``.

    Leaves keep self weight ``1 - 1/size`` and give/get ``1/size`` to/from
    the center; the center's self weight is ``1/size``.
    """
    assert size > 0
    w = np.zeros((size, size))
    np.fill_diagonal(w, 1.0 - 1.0 / size)
    w[center_rank, :] = 1.0 / size
    w[:, center_rank] = 1.0 / size
    return nx.from_numpy_array(w, create_using=nx.DiGraph)


def RingGraph(size: int, connect_style: int = 0) -> nx.DiGraph:
    """Ring topology.

    ``connect_style``: 0 = bidirectional (weights 1/3 self/left/right),
    1 = left-connection only, 2 = right-connection only (weights 1/2 each).
    """
    assert size > 0
    if connect_style not in (0, 1, 2):
        raise ValueError("connect_style must be 0 (bi), 1 (left) or 2 (right)")
    if size == 1:
        return nx.from_numpy_array(np.ones((1, 1)), create_using=nx.DiGraph)
    if size == 2:
        return nx.from_numpy_array(np.full((2, 2), 0.5), create_using=nx.DiGraph)

    row = np.zeros(size)
    if connect_style == 0:
        row[[0, 1, -1]] = 1.0 / 3.0
    elif connect_style == 1:
        row[[0, -1]] = 0.5
    else:
        row[[0, 1]] = 0.5
    return _from_circulant_row(row)


def FullyConnectedGraph(size: int) -> nx.DiGraph:
    """Complete graph with uniform ``1/size`` weights (centralized averaging)."""
    assert size > 0
    return _from_circulant_row(np.full(size, 1.0 / size))


def IsTopologyEquivalent(topo1: Optional[nx.DiGraph], topo2: Optional[nx.DiGraph]) -> bool:
    """Exact equality of the two weighted adjacency matrices (not isomorphism)."""
    if topo1 is None or topo2 is None:
        return False
    if topo1.number_of_nodes() != topo2.number_of_nodes():
        return False
    if topo1.number_of_edges() != topo2.number_of_edges():
        return False
    return bool(np.array_equal(nx.to_numpy_array(topo1), nx.to_numpy_array(topo2)))


def IsRegularGraph(topo: nx.DiGraph) -> bool:
    """True when every node has the same (in + out) degree."""
    degrees = {d for _, d in topo.degree()}
    return len(degrees) <= 1


def isPowerOf(x, base: int) -> bool:
    """True when ``x`` is an exact power of ``base`` (reference
    ``common/topology_util.py:90-96``, incl. its argument contracts)."""
    if not isinstance(base, int):
        raise AssertionError("Base has to be a integer.")
    if base <= 1:
        raise AssertionError("Base has to a interger larger than 1.")
    if x <= 0:
        raise AssertionError("x must be positive")
    p = 1
    while p < x:
        p *= base
    return p == x


def GetRecvWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """(self_weight, {src_rank: weight}) with which ``rank`` averages inputs."""
    w = nx.to_numpy_array(topo)
    neighbor_weights = {
        int(src): float(w[src, rank])
        for src in topo.predecessors(rank)
        if src != rank
    }
    self_weight = float(w[rank, rank]) if topo.has_edge(rank, rank) else 0.0
    return self_weight, neighbor_weights


def GetSendWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """(self_weight, {dst_rank: weight}) describing what ``rank`` sends out."""
    w = nx.to_numpy_array(topo)
    neighbor_weights = {
        int(dst): float(w[rank, dst])
        for dst in topo.successors(rank)
        if dst != rank
    }
    self_weight = float(w[rank, rank]) if topo.has_edge(rank, rank) else 0.0
    return self_weight, neighbor_weights
