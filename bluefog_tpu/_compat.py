"""Compatibility shims for older JAX releases (0.4.x).

The package is written against the current JAX surface (``jax.shard_map``,
``check_vma=``, ``pltpu.CompilerParams``, ``pltpu.InterpretParams``,
``jax.typeof``).  Older releases spell these differently or lack them:

  new name                       old (0.4.x) name
  ----------------------------   --------------------------------------
  jax.shard_map                  jax.experimental.shard_map.shard_map
  shard_map(check_vma=...)       shard_map(check_rep=...)
  pltpu.CompilerParams           pltpu.TPUCompilerParams
  pltpu.InterpretParams()        pallas_call(interpret=True)
  jax.typeof(x)                  (absent; only used for .vma probing)

:func:`install` aliases the new names onto the old ones when they are
missing, so every call site (library and tests) can use the current
spelling unconditionally.  On a current JAX it is a no-op.  Installed
from ``bluefog_tpu/__init__`` before any submodule import.
"""

import functools

import jax

__all__ = ["install", "JAX_PRE_05"]


def _version_tuple(version: str):
    parts = []
    for p in version.split(".")[:2]:
        digits = "".join(c for c in p if c.isdigit())
        parts.append(int(digits or 0))
    return tuple(parts)


# Capability flag for old-JAX hosts: jaxlib < 0.5 has no Mosaic
# TPU-simulating interpreter (the fused kernel's DMA semaphores have no CPU
# lowering) and no multiprocess CPU backend.  Shared by tests/conftest.py
# and __graft_entry__.py so the expression lives in exactly one place.
JAX_PRE_05 = _version_tuple(jax.__version__) < (0, 5)


def _shard_map_shim():
    from jax.experimental.shard_map import shard_map as _legacy

    @functools.wraps(_legacy)
    def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                  check_vma=True, **kwargs):
        # 0.4.x check_rep is the precursor of check_vma, but its
        # replication inference rejects valid programs around ppermute /
        # all_gather compositions that check_vma accepts; since the shim
        # only ever runs on 0.4.x, disable the check rather than
        # translate the flag.
        del check_vma
        kwargs.pop("axis_names", None)
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False, **kwargs)

    return shard_map


def install() -> None:
    """Install the aliases (idempotent; no-op on a current JAX)."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_shim()

    if not hasattr(jax.lax, "axis_size"):
        # psum of the literal 1 is special-cased to the static axis size
        # (no collective is emitted), which is exactly axis_size's contract
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

    if not hasattr(jax.lax, "pcast"):
        # pcast adjusts varying-mesh-axes TYPES only (no data movement); on
        # 0.4.x there is no vma tracking (the shim runs shard_map with
        # check_rep=False), so the identity is the faithful translation
        def _pcast(x, axis_name=None, *, to=None):
            del axis_name, to
            return x
        jax.lax.pcast = _pcast

    import inspect
    if "simple" not in inspect.signature(jax.tree_util.keystr).parameters:
        _keystr_legacy = jax.tree_util.keystr

        def keystr(keypath, *, simple=False, separator=None):
            if not simple and separator is None:
                return _keystr_legacy(keypath)
            # emulate simple mode: bare entry names joined by the separator
            parts = []
            for entry in keypath:
                for attr in ("key", "name", "idx"):
                    if hasattr(entry, attr):
                        parts.append(str(getattr(entry, attr)))
                        break
                else:
                    parts.append(str(entry))
            return (separator or "").join(parts)

        jax.tree_util.keystr = keystr

    if not hasattr(jax, "typeof"):
        # only used to probe varying-mesh-axes (``.vma``) on values, an
        # attribute that does not exist on 0.4.x avals — returning the
        # value itself makes every ``getattr(jax.typeof(x), "vma", ())``
        # probe come back empty, which is correct for this JAX
        jax.typeof = lambda x: x

    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pallas not importable at all: nothing to alias
        return

    if not hasattr(pltpu, "CompilerParams") and hasattr(pltpu,
                                                        "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams

    if not hasattr(pltpu, "InterpretParams"):
        # 0.4.x has no TPU-simulating interpreter; ``interpret=True``
        # (the generic pallas interpreter) is the closest behavior, and
        # the call sites all pass the instance straight into
        # ``pallas_call(interpret=...)``
        def _interpret_params(**_kwargs):
            return True
        pltpu.InterpretParams = _interpret_params
