"""Loader for the native runtime library (``csrc/`` → ``libbluefog_native.so``).

The reference ships its native core as a compiled extension built by
``setup.py``'s compile-probing machinery (reference setup.py:155-237).  Here
the native pieces are host-side runtime services (timeline writer, window
driver) — the TPU compute path is XLA — so a plain shared library consumed
over ctypes is the right shape: no Python C-API coupling, trivially
rebuildable, loadable from any interpreter.

The library is built on demand with ``g++ -O2 -shared -fPIC`` the first time
it is needed (cached next to the sources, guarded by a lock file so parallel
test workers don't race).  Everything degrades gracefully: if no toolchain is
available, callers fall back to pure-Python implementations.
"""

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger("bluefog_tpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CSRC = os.path.join(_ROOT, "csrc")
_BUILD_DIR = os.path.join(_CSRC, "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libbluefog_native.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


def _sources():
    return sorted(
        os.path.join(_CSRC, f) for f in os.listdir(_CSRC) if f.endswith(".cc"))


def _needs_build(sources):
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in sources)


def build(force: bool = False) -> str:
    """Compile ``csrc/*.cc`` into the shared library; returns its path."""
    sources = _sources()
    if not sources:
        raise FileNotFoundError(f"no C++ sources under {_CSRC}")
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if not force and not _needs_build(sources):
        return _LIB_PATH
    lockfile = _LIB_PATH + ".lock"
    fd = os.open(lockfile, os.O_CREAT | os.O_RDWR)
    try:
        import fcntl
        fcntl.flock(fd, fcntl.LOCK_EX)
        if force or _needs_build(sources):
            tmp = _LIB_PATH + ".tmp"
            cmd = ["g++", "-std=c++17", "-O2", "-shared", "-fPIC",
                   "-pthread", "-o", tmp] + sources
            logger.debug("building native lib: %s", " ".join(cmd))
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _LIB_PATH)
    finally:
        os.close(fd)
    return _LIB_PATH


def load():
    """Load (building if necessary) the native library, or None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            path = build()
            lib = ctypes.CDLL(path)
            _declare(lib)
            _lib = lib
        except Exception as e:  # toolchain missing, etc. — fall back to Python
            logger.warning("native library unavailable (%s); using pure-Python "
                           "fallbacks", e)
            _load_failed = True
    return _lib


def _declare(lib):
    lib.bft_timeline_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bft_timeline_open.restype = ctypes.c_int
    lib.bft_timeline_close.argtypes = []
    lib.bft_timeline_close.restype = None
    lib.bft_timeline_active.argtypes = []
    lib.bft_timeline_active.restype = ctypes.c_int
    lib.bft_timeline_record.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char, ctypes.c_int64]
    lib.bft_timeline_record.restype = None
    lib.bft_timeline_record_at.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char, ctypes.c_int64,
        ctypes.c_int64]
    lib.bft_timeline_record_at.restype = None
    lib.bft_timeline_counter.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_double, ctypes.c_int64]
    lib.bft_timeline_counter.restype = None
    lib.bft_timeline_now_us.argtypes = []
    lib.bft_timeline_now_us.restype = ctypes.c_int64
    lib.bft_timeline_dropped.argtypes = []
    lib.bft_timeline_dropped.restype = ctypes.c_int64
    # logging.cc
    lib.bft_log.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
    lib.bft_log.restype = None
    lib.bft_log_level.argtypes = []
    lib.bft_log_level.restype = ctypes.c_int
    lib.bft_log_set_level.argtypes = [ctypes.c_int]
    lib.bft_log_set_level.restype = None
    lib.bft_log_enabled.argtypes = [ctypes.c_int]
    lib.bft_log_enabled.restype = ctypes.c_int
    # service.cc
    lib.bft_service_start.argtypes = [ctypes.c_int]
    lib.bft_service_start.restype = ctypes.c_int
    lib.bft_service_stop.argtypes = []
    lib.bft_service_stop.restype = None
    lib.bft_service_running.argtypes = []
    lib.bft_service_running.restype = ctypes.c_int
    lib.bft_service_set_stall_warning_ms.argtypes = [ctypes.c_int64]
    lib.bft_service_set_stall_warning_ms.restype = None
    lib.bft_service_submit.argtypes = [SERVICE_CALLBACK, ctypes.c_int64,
                                       ctypes.c_int]
    lib.bft_service_submit.restype = ctypes.c_int64
    lib.bft_handle_alloc.argtypes = []
    lib.bft_handle_alloc.restype = ctypes.c_int64
    lib.bft_handle_mark_done.argtypes = [ctypes.c_int64]
    lib.bft_handle_mark_done.restype = None
    lib.bft_handle_mark_error.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.bft_handle_mark_error.restype = None
    lib.bft_handle_poll.argtypes = [ctypes.c_int64]
    lib.bft_handle_poll.restype = ctypes.c_int
    lib.bft_handle_wait.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.bft_handle_wait.restype = ctypes.c_int
    lib.bft_handle_error_msg.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                         ctypes.c_int]
    lib.bft_handle_error_msg.restype = ctypes.c_int
    lib.bft_handle_release.argtypes = [ctypes.c_int64]
    lib.bft_handle_release.restype = None
    lib.bft_service_pending.argtypes = []
    lib.bft_service_pending.restype = ctypes.c_int64


# worker-side task entry: cb(handle, tag) — ctypes re-acquires the GIL for
# the Python trampoline, mirroring the reference's C++-thread -> torch
# callback boundary (torch/mpi_ops.cc:85-97)
SERVICE_CALLBACK = ctypes.CFUNCTYPE(None, ctypes.c_int64, ctypes.c_int64)
